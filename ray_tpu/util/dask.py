"""Dask-on-ray_tpu: execute dask task graphs on the cluster.

Reference analog: ``python/ray/util/dask/scheduler.py`` —
``ray_dask_get`` walks a dask graph and submits one ray task per graph
task, passing upstream results as ObjectRefs so the object plane (not
the driver) carries intermediate data. This implementation speaks the
dask graph *protocol* directly (a graph is a dict of key -> literal |
key | ``(callable, *args)`` with keys/nested lists inside args), so the
scheduler core works — and is tested — without dask installed; when
dask IS present, ``enable_dask_on_ray`` registers it as the default
``dask.config`` scheduler exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Hashable

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray"]


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x) -> bool:
    """Dask task spec: a tuple whose head is callable."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


class _Dep:
    """Placeholder for the i-th dependency inside a shipped expression
    (rebuilt executor-side from the materialized top-level args)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _rebuild(expr, deps):
    """Executor-side: run a task expression with deps substituted."""
    if isinstance(expr, _Dep):
        return deps[expr.i]
    if _istask(expr):
        fn = expr[0]
        return fn(*[_rebuild(a, deps) for a in expr[1:]])
    if isinstance(expr, list):
        return [_rebuild(a, deps) for a in expr]
    if isinstance(expr, tuple):
        return tuple(_rebuild(a, deps) for a in expr)
    if isinstance(expr, dict):
        return {k: _rebuild(v, deps) for k, v in expr.items()}
    return expr


def _exec_task(expr, *deps):
    return _rebuild(expr, deps)


def ray_dask_get(dsk: dict, keys, ray_remote_args: dict | None = None,
                 **kwargs) -> Any:
    """Dask scheduler entry point (``dask.compute(scheduler=ray_dask_get)``
    or direct use). ``keys`` may be a single key or (nested) lists of
    keys; the result mirrors its structure. Each graph task becomes one
    cluster task; shared upstream keys are computed once and fan out as
    ObjectRefs."""
    remote = ray_tpu.remote(**(ray_remote_args or {}))(_exec_task) \
        if ray_remote_args else _exec_remote
    refs: dict[Hashable, Any] = {}     # key -> ObjectRef | literal
    visiting: set = set()

    def schedule(key):
        if key in refs:
            return refs[key]
        if key in visiting:
            raise ValueError(f"cycle in dask graph at key {key!r}")
        visiting.add(key)
        expr = dsk[key]
        try:
            if _istask(expr):
                shipped, deps = _extract(expr)
                refs[key] = remote.remote(shipped, *deps)
            elif _ishashable(expr) and expr in dsk and expr != key:
                refs[key] = schedule(expr)          # alias key
            elif isinstance(expr, (list, tuple, dict)) and _has_keys(expr):
                shipped, deps = _extract(expr)
                refs[key] = remote.remote(shipped, *deps)
            else:
                refs[key] = expr                    # plain literal
        finally:
            visiting.discard(key)
        return refs[key]

    def _has_keys(expr) -> bool:
        if _ishashable(expr) and expr in dsk:
            return True
        if isinstance(expr, (list, tuple)):
            return any(_has_keys(a) for a in expr)
        if isinstance(expr, dict):
            return any(_has_keys(v) for v in expr.values())
        return False

    def _extract(expr):
        """Replace graph-key references inside ``expr`` with _Dep
        placeholders; the keys' refs travel as TOP-LEVEL task args (the
        runtime materializes top-level ObjectRefs, same contract as the
        reference scheduler's unpack_object_refs)."""
        deps: list = []

        def walk(e):
            if _ishashable(e) and e in dsk:
                deps.append(schedule(e))
                return _Dep(len(deps) - 1)
            if _istask(e):
                return (e[0],) + tuple(walk(a) for a in e[1:])
            if isinstance(e, list):
                return [walk(a) for a in e]
            if isinstance(e, tuple):
                return tuple(walk(a) for a in e)
            if isinstance(e, dict):
                return {k: walk(v) for k, v in e.items()}
            return e

        return walk(expr), deps

    def resolve(k):
        if isinstance(k, list):
            return [resolve(x) for x in k]
        out = schedule(k)
        return ray_tpu.get(out) if isinstance(
            out, ray_tpu.ObjectRef) else out

    return resolve(keys)


_exec_remote = ray_tpu.remote(_exec_task)


def enable_dask_on_ray(**dask_config_kwargs):
    """Set ``ray_dask_get`` as dask's default scheduler (requires dask;
    the scheduler itself does not). Usable as a context manager, like
    the reference helper."""
    try:
        import dask
    except ImportError as e:                       # pragma: no cover
        raise ImportError(
            "enable_dask_on_ray requires dask; ray_dask_get itself "
            "works without it") from e
    return dask.config.set(scheduler=ray_dask_get, **dask_config_kwargs)
