"""ActorPool (reference API: ``python/ray/util/actor_pool.py:13``; the
bookkeeping here is this repo's own — work is tracked by a submission
serial, with a deque of queued calls and one in-flight table keyed both
ways)."""

from __future__ import annotations

from collections import deque

import ray_tpu
from ray_tpu.utils.exceptions import GetTimeoutError


class ActorPool:
    """Round-robin work distribution over a fixed set of actors with
    in-order (``map``) and completion-order (``map_unordered``) result
    iteration."""

    def __init__(self, actors: list):
        self._idle = deque(actors)
        self._queued: deque = deque()      # (fn, value) waiting for an actor
        self._in_flight: dict = {}         # serial -> (ref, actor)
        self._serial_of: dict = {}         # ref -> serial
        self._submitted = 0                # serials handed out
        self._yielded = 0                  # next serial get_next() returns

    # -- submission ----------------------------------------------------

    def submit(self, fn, value):
        """fn(actor, value) -> ObjectRef; queued if all actors busy."""
        if self._idle:
            self._launch(fn, value)
        else:
            self._queued.append((fn, value))

    def _launch(self, fn, value):
        actor = self._idle.popleft()
        ref = fn(actor, value)
        self._in_flight[self._submitted] = (ref, actor)
        self._serial_of[ref] = self._submitted
        self._submitted += 1

    def _recycle(self, serial):
        ref, actor = self._in_flight.pop(serial)
        self._serial_of.pop(ref, None)
        self._idle.append(actor)
        if self._queued:
            self._launch(*self._queued.popleft())

    # -- results -------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._in_flight) or bool(self._queued)

    def get_next(self, timeout=None):
        """Next result in SUBMISSION order."""
        # slots consumed out-of-order by get_next_unordered leave holes;
        # the in-order cursor walks past them
        while (self._yielded < self._submitted
               and self._yielded not in self._in_flight):
            self._yielded += 1
        if self._yielded not in self._in_flight:
            raise StopIteration("no pending results")
        serial = self._yielded
        ref = self._in_flight[serial][0]
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            # nothing consumed: the same serial is retrievable on retry,
            # and the still-busy actor is NOT recycled
            raise
        except BaseException:
            # the task errored but the actor itself is healthy — consume
            # the slot and recycle so queued submits aren't stranded
            self._yielded = serial + 1
            self._recycle(serial)
            raise
        self._yielded = serial + 1
        self._recycle(serial)
        return value

    def get_next_unordered(self, timeout=None):
        """Next result in COMPLETION order."""
        if not self._in_flight:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait([r for r, _ in self._in_flight.values()],
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        serial = self._serial_of[ref]
        try:
            return ray_tpu.get(ref)
        finally:
            self._recycle(serial)

    def map(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- manual actor management ---------------------------------------

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
