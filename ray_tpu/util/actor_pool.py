"""ActorPool (reference: ``python/ray/util/actor_pool.py:13``)."""

from __future__ import annotations

import ray_tpu


class ActorPool:
    """Round-robin work distribution over a fixed set of actors with
    in-order (``map``) and completion-order (``map_unordered``) result
    iteration."""

    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn, value):
        """fn(actor, value) -> ObjectRef; queued if all actors busy."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout=None):
        """Next result in SUBMISSION order."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        try:
            return ray_tpu.get(ref, timeout=timeout)
        finally:
            # even when the task errored, the actor itself is healthy —
            # return it so queued submits aren't stranded
            self._return_actor(ref)

    def get_next_unordered(self, timeout=None):
        """Next result in COMPLETION order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        index, _ = self._future_to_actor[ref]
        self._index_to_future.pop(index, None)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(ref)

    def _return_actor(self, ref):
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def map(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
