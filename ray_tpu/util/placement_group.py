"""Placement group public API.

Reference analog: ``python/ray/util/placement_group.py`` — bundles +
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD strategies, 2-phase reservation on
the GCS (SURVEY N1: GcsPlacementGroupManager). The TPU twist: a bundle
may carry a ``TPU`` demand, and slice-aware packing keeps bundles
ICI-adjacent by preferring single-node PACK.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ray_tpu.runtime import core as _core
from ray_tpu.utils.ids import PlacementGroupID


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: list
    strategy: str

    def ready(self, timeout: float = 30.0) -> bool:
        rt = _core.get_runtime()
        if not hasattr(rt, "_gcs"):
            return True  # local mode: trivially placed
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = rt._gcs.call("get_placement_group", pg_id=self.id.hex())
            if info and info["state"] == "CREATED":
                return True
            time.sleep(0.05)
        return False

    @property
    def bundle_specs(self) -> list:
        return list(self.bundles)


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    rt = _core.get_runtime()
    pg_id = PlacementGroupID.from_random()
    if hasattr(rt, "_gcs"):
        rt._gcs.call("create_placement_group", pg_id=pg_id.hex(),
                     bundles=[dict(b) for b in bundles], strategy=strategy)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup):
    rt = _core.get_runtime()
    if hasattr(rt, "_gcs"):
        rt._gcs.call("remove_placement_group", pg_id=pg.id.hex())


def placement_group_table(pg: PlacementGroup | None = None) -> dict:
    rt = _core.get_runtime()
    if not hasattr(rt, "_gcs"):
        return {}
    if pg is not None:
        info = rt._gcs.call("get_placement_group", pg_id=pg.id.hex())
        return info or {}
    return {p["pg_id"]: p
            for p in rt._gcs.call("list_placement_groups")}


class PlacementGroupSchedulingStrategy:
    """Pass as ``scheduling_strategy=`` in task/actor options (reference:
    ``util/scheduling_strategies.py``)."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1):
        self.placement_group = placement_group
        self.bundle_index = placement_group_bundle_index
