"""multiprocessing.Pool API over ray_tpu tasks.

Reference analog: ``python/ray/util/multiprocessing/`` (P22) — drop-in
Pool so existing ``multiprocessing`` code scales across the cluster
without rewrites. Functions ship via the runtime's cloudpickle path, so
lambdas/closures work (unlike stdlib multiprocessing).
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Callable, Iterable

import ray_tpu

# Pools whose initializer already ran IN THIS PROCESS (workers import
# this module, so the set is per worker process — giving the stdlib's
# once-per-worker initializer semantics instead of once-per-task).
_initialized_pools: set[str] = set()


class AsyncResult:
    def __init__(self, refs, *, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout=None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout=None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            # stdlib contract: pending is not failure
            raise ValueError("AsyncResult not ready")
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Pool(processes) — processes caps per-task resources only in
    spirit; the runtime schedules by resources, so `processes` simply
    bounds chunking for map."""

    def __init__(self, processes: int | None = None,
                 initializer: Callable | None = None, initargs=()):
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = tuple(initargs)
        self._closed = False
        self._pool_id = uuid.uuid4().hex

    # -- submission ------------------------------------------------------

    def _task(self, fn):
        init, initargs = self._initializer, self._initargs
        pool_id = self._pool_id

        def run(*args, **kwargs):
            if init is not None:
                from ray_tpu.util.multiprocessing import _initialized_pools

                if pool_id not in _initialized_pools:
                    init(*initargs)  # a failed init is retried next task
                    _initialized_pools.add(pool_id)
            return fn(*args, **kwargs)

        return ray_tpu.remote(run)

    def apply(self, fn, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None) -> AsyncResult:
        self._check_open()
        ref = self._task(fn).remote(*args, **(kwds or {}))
        return AsyncResult([ref], single=True)

    def map(self, fn, iterable: Iterable, chunksize: int | None = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        items = list(iterable)
        chunks = self._chunk(items, chunksize)
        task = self._task(lambda chunk: [fn(x) for x in chunk])
        refs = [task.remote(c) for c in chunks]

        class _FlatResult(AsyncResult):
            def get(self, timeout=None):
                nested = ray_tpu.get(self._refs, timeout=timeout)
                return list(itertools.chain.from_iterable(nested))

        return _FlatResult(refs, single=False)

    def starmap(self, fn, iterable):
        items = list(iterable)
        task = self._task(lambda chunk: [fn(*x) for x in chunk])
        chunks = self._chunk(items, None)
        refs = [task.remote(c) for c in chunks]
        nested = ray_tpu.get(refs)
        return list(itertools.chain.from_iterable(nested))

    def imap(self, fn, iterable, chunksize: int | None = None):
        """Ordered iterator over results. Submission is EAGER (stdlib
        semantics: the pool may be closed while results are consumed);
        chunksize batches items per task."""
        self._check_open()
        task = self._task(lambda chunk: [fn(x) for x in chunk])
        chunks = self._chunk(list(iterable), chunksize)
        refs = [task.remote(c) for c in chunks]

        def gen():
            for ref in refs:
                yield from ray_tpu.get(ref)

        return gen()

    def imap_unordered(self, fn, iterable, chunksize: int | None = None):
        self._check_open()
        task = self._task(lambda chunk: [fn(x) for x in chunk])
        chunks = self._chunk(list(iterable), chunksize)
        refs = [task.remote(c) for c in chunks]

        def gen():
            pending = list(refs)
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=1)
                yield from ray_tpu.get(ready[0])

        return gen()

    # -- lifecycle -------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass  # tasks are independent; nothing to join

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def _chunk(self, items: list, chunksize: int | None) -> list[list]:
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]
