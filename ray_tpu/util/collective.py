"""Host-level collective communication between actors/tasks.

Reference analog: ``python/ray/util/collective/`` (P-COLL —
``GroupManager:40``, ``init_collective_group:120``, ``allreduce:258``,
``send:531``) which wraps NCCL/Gloo. The TPU device plane does NOT use
this — ICI collectives are XLA ops inside jit (``ray_tpu.parallel``); this
module is the Gloo analog for host (CPU/numpy) tensors: rendezvous through
a named coordinator actor per group, with numpy reductions.

API parity: init_collective_group, allreduce, allgather, reducescatter,
broadcast, barrier, send/recv (point-to-point through the coordinator).
"""

from __future__ import annotations

import threading
import time

import numpy as np

import ray_tpu

_REDUCERS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
}


class _Coordinator:
    """Rendezvous actor: collects per-rank contributions round by round,
    computes the collective, and hands each rank its share."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: dict = {}     # (op_name, round_id) -> {rank: array}
        self.results: dict = {}    # (op_name, round_id) -> result
        self.mailbox: dict = {}    # (src, dst, tag) -> value
        # contribute() runs on concurrent actor threads (max_concurrency>1)
        self._lock = threading.Lock()

    def contribute(self, op_name, round_id, rank, value, spec=None):
        key = (op_name, round_id)
        with self._lock:
            slot = self.rounds.setdefault(key, {})
            slot[rank] = value
            if len(slot) == self.world_size and key in self.rounds:
                self.results[key] = self._compute(op_name, slot, spec)
                del self.rounds[key]
        return True

    def fetch(self, op_name, round_id, rank):
        key = (op_name, round_id)
        with self._lock:
            if key not in self.results:
                return False, None
            result = self.results[key]
        if op_name.startswith("reducescatter"):
            out = result[rank]
        elif op_name.startswith("broadcast"):
            out = result
        else:
            out = result
        return True, out

    def gc_round(self, op_name, round_id):
        with self._lock:
            self.results.pop((op_name, round_id), None)
        return True

    def _compute(self, op_name, slot, spec):
        values = [slot[r] for r in sorted(slot)]
        if op_name.startswith("allreduce"):
            return _REDUCERS[spec or "sum"](
                [np.asarray(v) for v in values])
        if op_name.startswith("allgather"):
            return list(values)
        if op_name.startswith("reducescatter"):
            reduced = _REDUCERS[spec or "sum"](
                [np.asarray(v) for v in values])
            return np.array_split(reduced, self.world_size)
        if op_name.startswith("broadcast"):
            return values[int(spec or 0)]
        if op_name.startswith("barrier"):
            return True
        raise ValueError(op_name)

    def post(self, src, dst, tag, value):
        with self._lock:
            self.mailbox[(src, dst, tag)] = value
        return True

    def take(self, src, dst, tag):
        with self._lock:
            if (src, dst, tag) in self.mailbox:
                return True, self.mailbox.pop((src, dst, tag))
        return False, None

    # -- address rendezvous (epoch-based, safe across group re-init) ----
    # A plain collective round would be wrong here: this named actor
    # outlives group incarnations, and a re-init with the same group name
    # must not see a previous incarnation's frozen round-0 result. Each
    # caller posts (rank, addr, uid); an epoch freezes when every rank
    # has one queued entry, and results are keyed by the per-incarnation
    # uid, so overlapping incarnations pair up FIFO per rank.

    def rdv_post(self, rank, addr, uid):
        with self._lock:
            pending = self.__dict__.setdefault("rdv_pending", {})
            done = self.__dict__.setdefault("rdv_done", {})
            pending.setdefault(rank, []).append((uid, addr))
            if all(pending.get(r) for r in range(self.world_size)):
                entries = [pending[r].pop(0)
                           for r in range(self.world_size)]
                peers = [a for _, a in entries]
                for u, _ in entries:
                    done[u] = peers
        return True

    def rdv_fetch(self, uid):
        with self._lock:
            done = self.__dict__.setdefault("rdv_done", {})
            if uid in done:
                return True, done.pop(uid)
        return False, None

    def rdv_abandon(self, rank, uid):
        """Withdraw a posted-but-unpaired entry (caller timed out). This
        keeps a crashed/given-up incarnation from sitting at the head of
        the rank's FIFO and poisoning every later epoch with a dead
        address."""
        with self._lock:
            pending = self.__dict__.setdefault("rdv_pending", {})
            q = pending.get(rank, [])
            pending[rank] = [(u, a) for (u, a) in q if u != uid]
            self.__dict__.setdefault("rdv_done", {}).pop(uid, None)
        return True


class CollectiveGroup:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        name = f"__collective_{group_name}"
        try:
            self.coord = ray_tpu.get_actor(name)
        except ValueError:
            cls = ray_tpu.remote(_Coordinator)
            try:
                self.coord = cls.options(name=name,
                                         max_concurrency=max(
                                             4, world_size)).remote(world_size)
            except ValueError:
                self.coord = ray_tpu.get_actor(name)

    def _collective(self, op: str, value, spec=None, timeout=60.0):
        round_id = self._round
        self._round += 1
        ray_tpu.get(self.coord.contribute.remote(
            op, round_id, self.rank, value, spec))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ok, out = ray_tpu.get(self.coord.fetch.remote(
                op, round_id, self.rank))
            if ok:
                if self.rank == 0:
                    # rank 0 GCs the round after a grace period; cheap and
                    # avoids unbounded result growth
                    self._maybe_gc(op, round_id)
                return out
            time.sleep(0.002)
        raise TimeoutError(
            f"collective {op} round {round_id} timed out in "
            f"group {self.group_name!r}")

    def _maybe_gc(self, op, round_id, keep: int = 8):
        if round_id >= keep:
            self.coord.gc_round.remote(op, round_id - keep)

    # -- the API (numpy in, numpy out) ----------------------------------
    def allreduce(self, array, op: str = "sum"):
        return self._collective("allreduce", np.asarray(array), op)

    def allgather(self, array) -> list:
        return self._collective("allgather", np.asarray(array))

    def reducescatter(self, array, op: str = "sum"):
        return self._collective("reducescatter", np.asarray(array), op)

    def broadcast(self, array, src_rank: int = 0):
        return self._collective("broadcast", np.asarray(array),
                                str(src_rank))

    def barrier(self):
        return self._collective("barrier", self.rank)

    def send(self, array, dst_rank: int, tag: int = 0):
        ray_tpu.get(self.coord.post.remote(
            self.rank, dst_rank, tag, np.asarray(array)))

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ok, value = ray_tpu.get(self.coord.take.remote(
                src_rank, self.rank, tag))
            if ok:
                return value
            time.sleep(0.002)
        raise TimeoutError(f"recv from rank {src_rank} timed out")

    def destroy(self):
        """Release backend resources (no-op for the actor backend; the
        named coordinator outlives incarnations by design)."""


class TcpCollectiveGroup(CollectiveGroup):
    """Direct rank-to-rank data plane over the C++ TCP backend
    (src/collective/tcp_collective.cc): ring allreduce etc. without the
    coordinator-actor hop. The actor is used ONCE, for address
    rendezvous; all tensor bytes then move peer-to-peer.

    Analog of the reference's gloo collective group
    (``collective_group/gloo_collective_group.py``) with the rendezvous
    store replaced by the named coordinator actor.
    """

    def __init__(self, group_name: str, world_size: int, rank: int):
        super().__init__(group_name, world_size, rank)
        import uuid

        from ray_tpu._private.tcp_collective import TcpGroup

        # Bind the listener FIRST (ephemeral port), then advertise the
        # actually-bound address — no reserve/close/rebind race.
        tcp = TcpGroup.listen(rank, world_size)
        host = "127.0.0.1"
        uid = uuid.uuid4().hex
        ray_tpu.get(self.coord.rdv_post.remote(
            rank, f"{host}:{tcp.port}", uid))
        deadline = time.monotonic() + 60.0
        while True:
            ok, peers = ray_tpu.get(self.coord.rdv_fetch.remote(uid))
            if ok:
                break
            if time.monotonic() > deadline:
                # withdraw our entry so this incarnation can't poison
                # later epochs with a dead listener address
                ray_tpu.get(self.coord.rdv_abandon.remote(rank, uid))
                raise TimeoutError(
                    f"collective group {group_name!r} rendezvous timed out")
            time.sleep(0.002)
        self._tcp = tcp.connect([str(a) for a in peers])

    def allreduce(self, array, op: str = "sum"):
        return self._tcp.allreduce(array, op)

    def allgather(self, array) -> list:
        return self._tcp.allgather(array)

    def reducescatter(self, array, op: str = "sum"):
        return self._tcp.reducescatter(array, op)

    def broadcast(self, array, src_rank: int = 0):
        return self._tcp.broadcast(array, src_rank)

    def barrier(self):
        self._tcp.barrier()
        return True

    def send(self, array, dst_rank: int, tag: int = 0):
        self._tcp.send(array, dst_rank, tag)

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 60.0):
        return self._tcp.recv(src_rank, tag, timeout=timeout)

    def destroy(self):
        self._tcp.destroy()


_groups = threading.local()


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default",
                          backend: str = "actor") -> CollectiveGroup:
    """``backend="actor"``: rendezvous-actor star (works anywhere, object
    path). ``backend="tcp"``: C++ ring collectives over direct sockets —
    the high-bandwidth host data plane."""
    if backend == "tcp":
        group = TcpCollectiveGroup(group_name, world_size, rank)
    else:
        group = CollectiveGroup(group_name, world_size, rank)
    if not hasattr(_groups, "groups"):
        _groups.groups = {}
    _groups.groups[group_name] = group
    return group


def get_group(group_name: str = "default") -> CollectiveGroup:
    groups = getattr(_groups, "groups", {})
    if group_name not in groups:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return groups[group_name]


def allreduce(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(array, op)


def allgather(array, group_name: str = "default"):
    return get_group(group_name).allgather(array)


def reducescatter(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(array, op)


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(array, src_rank)


def barrier(group_name: str = "default"):
    return get_group(group_name).barrier()


def send(array, dst_rank: int, group_name: str = "default", tag: int = 0):
    """Point-to-point send (reference: collective.py:531)."""
    return get_group(group_name).send(array, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = 60.0):
    """Point-to-point receive; returns the array."""
    return get_group(group_name).recv(src_rank, tag, timeout)
