"""AccelerateTrainer: HuggingFace Accelerate loops on rank workers.

Reference analog: ``train/huggingface/accelerate/accelerate_trainer.py``
— the reference validates an ``accelerate_config`` (path or dict),
materializes it per rank worker (Accelerate reads its config through
env vars / a config file at ``Accelerator()`` construction), and runs
the user loop under the torch process group. Same contract here:
``accelerate.Accelerator()`` constructed inside the loop discovers the
gloo process group the torch backend already initialized — RANK /
WORLD_SIZE env vars are set per rank actor — so
``accelerator.prepare(model, optimizer, loader)`` gives the standard
Accelerate DDP behavior.
"""

from __future__ import annotations

import os

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.torch import TorchConfig, TorchTrainer

# accelerate_config keys materialized as ACCELERATE_* env vars (the
# subset Accelerate reads from the environment; reference:
# accelerate_trainer.py's AccelerateConfig handling)
_ENV_KEYS = {
    "mixed_precision": "ACCELERATE_MIXED_PRECISION",
    "cpu": "ACCELERATE_USE_CPU",
    "dynamo_backend": "ACCELERATE_DYNAMO_BACKEND",
    "gradient_accumulation_steps": "ACCELERATE_GRADIENT_ACCUMULATION_STEPS",
}


def _parse_accelerate_config(text: str, path: str = "<config>") -> dict:
    """Parse an Accelerate config file: JSON, then real YAML when the
    ``yaml`` package is importable, then a flat ``key: value`` fallback.

    The fallback REJECTS structured YAML instead of silently mangling it
    — the old line-splitter turned nested blocks into garbage entries
    like ``{"deepspeed_config": "", "zero_stage": "3"}``, flattening
    child keys into the top level and erasing which section they
    belonged to."""
    import json

    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    try:
        import yaml
    except ImportError:
        yaml = None
    if yaml is not None:
        loaded = yaml.safe_load(text)
        if not isinstance(loaded, dict):
            raise ValueError(
                f"accelerate config {path!r} must parse to a mapping, "
                f"got {type(loaded).__name__}")
        return loaded
    out = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#") or line == "---":
            continue
        indented = raw[:1] in (" ", "\t")
        if indented or line.startswith("- "):
            raise ValueError(
                f"accelerate config {path!r} line {lineno}: nested YAML "
                f"structure ({line!r}) needs the `yaml` package, which "
                f"is not installed — flatten the config or use JSON")
        key, sep, value = line.partition(":")
        if not sep:
            raise ValueError(
                f"accelerate config {path!r} line {lineno}: expected "
                f"'key: value', got {line!r}")
        value = value.split("#", 1)[0].strip()
        if not value:
            raise ValueError(
                f"accelerate config {path!r} line {lineno}: {key.strip()!r}"
                f" opens a nested block, which needs the `yaml` package — "
                f"flatten the config or use JSON")
        out[key.strip()] = value
    return out


def _wrap_accelerate(train_loop_per_worker, accelerate_config: dict):
    def accelerate_loop(config):
        try:
            import accelerate  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "AccelerateTrainer requires the `accelerate` package on "
                "every rank worker (pip runtime_env or host install)"
            ) from e
        for key, env in _ENV_KEYS.items():
            if key in accelerate_config:
                value = accelerate_config[key]
                if isinstance(value, bool):
                    value = "true" if value else "false"
                os.environ[env] = str(value)
        # any remaining keys ride a config file (Accelerate's own
        # loader picks ACCELERATE_CONFIG_FILE up at Accelerator())
        rest = {k: v for k, v in accelerate_config.items()
                if k not in _ENV_KEYS}
        if rest:
            import json
            import tempfile

            fd, path = tempfile.mkstemp(prefix="accel_cfg_",
                                        suffix=".json")
            with os.fdopen(fd, "w") as f:
                json.dump({"compute_environment": "LOCAL_MACHINE",
                           "distributed_type": "MULTI_CPU", **rest}, f)
            os.environ["ACCELERATE_CONFIG_FILE"] = path
        return train_loop_per_worker(config)

    return accelerate_loop


class AccelerateTrainer(TorchTrainer):
    """``TorchTrainer`` that materializes an Accelerate config on every
    rank before running an Accelerate-style loop.

    Usage::

        def train_loop(config):
            from accelerate import Accelerator
            accelerator = Accelerator()   # reads the materialized config
            model, opt, loader = accelerator.prepare(model, opt, loader)
            for batch in loader:
                loss = model(**batch)
                accelerator.backward(loss)
                ...
                session.report({"loss": float(loss)})

        AccelerateTrainer(train_loop,
                          accelerate_config={"mixed_precision": "no",
                                             "cpu": True},
                          scaling_config=ScalingConfig(num_workers=2)).fit()
    """

    def __init__(self, train_loop_per_worker, *,
                 accelerate_config: dict | str | None = None,
                 train_loop_config: dict | None = None,
                 torch_config: TorchConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None):
        if isinstance(accelerate_config, str):
            # a path to an Accelerate yaml/json config: parsed here so a
            # bad path fails at submission, not on every rank
            with open(accelerate_config) as f:
                text = f.read()
            accelerate_config = _parse_accelerate_config(
                text, path=accelerate_config)
        super().__init__(
            _wrap_accelerate(train_loop_per_worker,
                             accelerate_config or {}),
            train_loop_config=train_loop_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
