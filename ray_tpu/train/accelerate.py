"""AccelerateTrainer: HuggingFace Accelerate loops on rank workers.

Reference analog: ``train/huggingface/accelerate/accelerate_trainer.py``.
``accelerate.Accelerator()`` constructed inside ``train_loop_per_worker``
discovers the torch.distributed (gloo) process group the torch backend
already initialized — RANK/WORLD_SIZE env vars are set per rank actor —
so ``accelerator.prepare(model, optimizer, loader)`` gives the standard
Accelerate DDP behavior with no extra configuration.
"""

from __future__ import annotations

from ray_tpu.train.torch import TorchTrainer


class AccelerateTrainer(TorchTrainer):
    """``TorchTrainer`` whose contract is an Accelerate-style loop.

    Usage::

        def train_loop(config):
            from accelerate import Accelerator
            accelerator = Accelerator(cpu=True)
            model, opt, loader = accelerator.prepare(model, opt, loader)
            for batch in loader:
                loss = model(**batch)
                accelerator.backward(loss)
                ...
                session.report({"loss": float(loss)})

        AccelerateTrainer(train_loop,
                          scaling_config=ScalingConfig(num_workers=2)).fit()
    """
