"""TorchTrainer: torch.distributed (gloo) data-parallel training.

Reference analog: ``train/torch/torch_trainer.py:14`` +
``train/torch/config.py:23,149`` (``_setup_torch_process_group:63``) and
``train/torch/train_loop_utils.py:74,116`` (``prepare_model`` /
``prepare_data_loader``). The TPU-native flagship is JaxTrainer (the
device plane is XLA, not NCCL); this exists for capability parity — CPU
torch models train data-parallel across rank-actor processes with the
same ``train_loop_per_worker`` + ``session.report`` surface.

Process-group rendezvous uses a file:// store in the trial directory
(ranks share a filesystem; the reference uses rank-0's TCP address).
Requires real process workers — i.e. a cluster runtime; with the
in-process local runtime use world_size=1.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train import session


@dataclass
class TorchConfig:
    backend: str = "gloo"      # CPU image: no nccl
    init_timeout_s: float = 60.0


def _wrap_torch(train_fn, torch_config: TorchConfig):
    """Boot/teardown the torch process group around the user loop
    (reference: _TorchBackend.on_start -> _setup_torch_process_group)."""

    def torch_loop(config):
        import torch.distributed as dist

        ctx = session.get_context()
        world = ctx.get_world_size()
        if world > 1:
            # containers often lack resolvable hostnames; loopback works
            # for single-host rank processes (multi-host: set explicitly)
            os.environ.setdefault("GLOO_SOCKET_IFNAME", "lo")
            # per-ATTEMPT store: the file must be fresh for each
            # process group (a stale store from a finished group wedges
            # re-initialization on retries)
            store_path = os.path.join(ctx.get_trial_dir(),
                                      "torch_pg_store")
            # torch-ecosystem libraries (HF Trainer, accelerate) detect
            # distribution from these env vars, NOT from an
            # already-initialized process group — without them they
            # silently fall back to single-process semantics (no data
            # sharding, no gradient averaging) on every rank
            os.environ["RANK"] = str(ctx.get_world_rank())
            os.environ["WORLD_SIZE"] = str(world)
            os.environ["LOCAL_RANK"] = str(ctx.get_local_rank())
            # accelerate validates these even though the group below is
            # initialized via the file store (it only falls back to
            # env:// when no group exists yet)
            os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
            os.environ.setdefault("MASTER_PORT", "29500")
            from datetime import timedelta

            dist.init_process_group(
                backend=torch_config.backend,
                init_method=f"file://{store_path}",
                rank=ctx.get_world_rank(), world_size=world,
                timeout=timedelta(
                    seconds=torch_config.init_timeout_s),
            )
        try:
            return train_fn(config)
        finally:
            if world > 1 and dist.is_initialized():
                dist.destroy_process_group()

    return torch_loop


class TorchTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *,
                 train_loop_config: dict | None = None,
                 torch_config: TorchConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None):
        super().__init__(
            _wrap_torch(train_loop_per_worker,
                        torch_config or TorchConfig()),
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )


def prepare_model(model):
    """DDP-wrap when a process group is live (reference:
    train_loop_utils.py:74)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


class _EpochedLoader:
    """Iterating advances the DistributedSampler epoch so shuffled
    shards re-permute each epoch (reference hooks set_epoch the same
    way)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader):
    """Re-build a DataLoader with a DistributedSampler so each rank sees
    its shard (reference: train_loop_utils.py:116). The original
    loader's shuffle setting is preserved (a sequential eval loader must
    NOT come back shuffled+padded with reordered predictions), and
    shuffled loaders re-permute per epoch via set_epoch."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    shuffled = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffled)
    loader = DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        pin_memory=data_loader.pin_memory,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )
    return _EpochedLoader(loader, sampler)
