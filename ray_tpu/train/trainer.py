"""JaxTrainer: mesh-sharded training harness for the model library.

TPU-native analog of the reference's ``TorchTrainer`` + ``_TorchBackend``
(``train/torch/torch_trainer.py:14``, ``train/torch/config.py:23,149``): where
the reference boots a torch.distributed process group per rank actor and wraps
the model in DDP/FSDP, here the "backend setup" is building a
`jax.sharding.Mesh` and placing one state pytree on it; the train step is one
jit-compiled SPMD program and XLA emits the collectives that DDP/NCCL would
have issued.

The driver-facing surface mirrors the reference: construct with config +
scaling options, call ``fit()``/``train_step()``, receive metrics.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.sharding import (
    PRESETS,
    ShardingRules,
    batch_sharding,
    tree_shardings,
)
from ray_tpu.train.state import TrainState, state_logical_axes


@dataclass
class TrainConfig:
    """Scaling + optimization config (reference: ``ScalingConfig`` +
    framework config, ``air/config.py``)."""

    mesh_axes: dict = field(default_factory=lambda: {"dp": -1})
    strategy: str = "fsdp"          # sharding preset name or ShardingRules
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    total_steps: int = 10000
    max_grad_norm: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    donate_state: bool = True
    # Fused chunked cross-entropy: never materializes the [B, S, vocab]
    # fp32 logits — chunked LM-head matmul + logsumexp in a checkpointed
    # scan. Essential at Llama-3 vocab scale (128k vocab = 8 GB of fp32
    # logits at 8x2048); at 32k vocab the recompute overhead measured
    # ~4% SLOWER on v5e, so it's opt-in.
    fused_loss: bool = False
    loss_chunk: int = 1024
    # Pipeline parallelism (strategy="pp_fsdp"): microbatch count (default
    # = pp size, the minimum that fills the pipeline). The schedule is
    # 1F1B (interleaved fwd/bwd, O(pipeline-depth) activation stash) —
    # autodiff-through-GPipe is NOT offered here because differentiating
    # through the pipelined region with the embedding/head outside trips an
    # XLA partitioner crash on multi-axis meshes (see
    # parallel/pipeline.py); forward-only GPipe remains available via
    # llama_forward_pipelined.
    n_microbatches: int | None = None


class JaxTrainer:
    """Single-controller trainer over one mesh.

    Usage::

        trainer = JaxTrainer(model_cfg, TrainConfig(mesh_axes={"dp":2,"fsdp":2,"tp":2}))
        state = trainer.init_state(jax.random.key(0))
        state, metrics = trainer.train_step(state, batch)  # batch: [B, S+1] tokens
    """

    def __init__(self, model_cfg, cfg: TrainConfig,
                 *, mesh: Mesh | None = None,
                 loss_fn: Callable | None = None):
        """``loss_fn(model_cfg, params, batch) -> scalar`` overrides the
        default next-token cross entropy — the hook that trains
        non-causal objectives (e.g. BERT MLM with a dict batch) through
        the same sharded-state machinery. Batch leaves must share the
        [B, ...] leading axis for data sharding."""
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.loss_fn = loss_fn
        # Model-family dispatch: any module exposing init_params /
        # param_logical_axes / forward over a frozen config dataclass
        # plugs in (llama is the flagship; gpt is the second decoder
        # family). Llama-only features (fused loss, ring attention,
        # 1F1B) are guarded below.
        self.family = self._resolve_family(model_cfg)
        self.mesh = mesh if mesh is not None else create_mesh(cfg.mesh_axes)
        self.rules: ShardingRules = (
            cfg.strategy if isinstance(cfg.strategy, ShardingRules)
            else PRESETS[cfg.strategy]
        )
        self.optimizer = self._make_optimizer()
        self._jit_step = {}
        # Sequence parallelism: use ring attention when the rules shard seq
        # over a mesh axis that actually exists on this mesh.
        sp = self.rules.seq
        self.attn_impl = (
            "ring" if sp is not None and sp in self.mesh.axis_names
            and self.mesh.shape[sp] > 1 else "auto"
        )
        self.sp_axis = sp if self.attn_impl == "ring" else "sp"
        # Pipeline parallelism: active when the rules map the stacked-layer
        # dim onto a mesh axis that exists with size > 1.
        ppax = self.rules.layers
        self.pp_axis = (
            ppax if isinstance(ppax, str) and ppax in self.mesh.axis_names
            and self.mesh.shape[ppax] > 1 else None
        )
        if self.family is not llama and (cfg.fused_loss
                                         or self.attn_impl == "ring"):
            raise ValueError(
                "fused_loss / ring attention are llama-only paths")
        if loss_fn is not None and (cfg.fused_loss or self.pp_axis):
            raise ValueError(
                "custom loss_fn cannot combine with fused_loss or "
                "pipeline parallelism (both own the loss computation)")
        # families without a causal-LM `forward` need the loss hook
        if loss_fn is None and not hasattr(self.family, "forward"):
            raise ValueError(
                f"{self.family.__name__} has no causal-LM default; pass "
                "loss_fn= (e.g. wrapping bert.mlm_loss)")
        if self.pp_axis:
            if self.family is not llama:
                raise ValueError(
                    "pipeline parallelism is wired for the llama family "
                    "only (make_llama_stage_fn)")
            n_pp = self.mesh.shape[self.pp_axis]
            if model_cfg.n_layers % n_pp:
                raise ValueError(
                    f"n_layers={model_cfg.n_layers} not divisible by "
                    f"pp={n_pp}"
                )
            if cfg.fused_loss:
                raise ValueError(
                    "fused_loss is redundant under pipeline parallelism: "
                    "the 1F1B loss slot already computes the head "
                    "per-microbatch"
                )

    @staticmethod
    def _resolve_family(model_cfg):
        if isinstance(model_cfg, llama.LlamaConfig):
            return llama
        from ray_tpu.models import bert, gpt

        if isinstance(model_cfg, gpt.GPTConfig):
            return gpt
        if isinstance(model_cfg, bert.BertConfig):
            return bert
        raise TypeError(
            f"unsupported model config {type(model_cfg).__name__}; "
            "expected LlamaConfig, GPTConfig, or BertConfig")

    # --- optimizer (AdamW + cosine schedule + clip, the Llama recipe) ---

    def _make_optimizer(self) -> optax.GradientTransformation:
        c = self.cfg
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=c.learning_rate,
            warmup_steps=c.warmup_steps,
            decay_steps=max(c.total_steps, c.warmup_steps + 1),
            end_value=c.learning_rate * 0.1,
        )
        return optax.chain(
            optax.clip_by_global_norm(c.max_grad_norm),
            optax.adamw(schedule, b1=c.b1, b2=c.b2,
                        weight_decay=c.weight_decay),
        )

    # --- state ---

    def _make_state_fn(self, key):
        params = self.family.init_params(self.model_cfg, key)
        return TrainState.create(params, self.optimizer)

    def _state_axes(self) -> TrainState:
        """Abstract-eval a state skeleton to derive per-leaf logical axes
        (optimizer moments inherit their param's axes — ZeRO-style)."""
        param_axes = self.family.param_logical_axes(self.model_cfg)
        return state_logical_axes(self.abstract_state(), param_axes)

    def _axes_to_sharding(self, ax) -> NamedSharding:
        from ray_tpu.parallel.sharding import logical_sharding

        if ax:
            return logical_sharding(tuple(ax), self.mesh, self.rules)
        return NamedSharding(self.mesh, P())

    def abstract_state(self) -> Any:
        """ShapeDtypeStruct pytree of a TrainState (shared by sharding
        derivation and checkpoint restore)."""
        return jax.eval_shape(self._make_state_fn, jax.random.key(0))

    def state_shardings(self) -> Any:
        """NamedSharding pytree for a TrainState (also used by checkpoint
        restore to place shards directly on devices)."""
        from ray_tpu.parallel.sharding import is_axes_leaf

        return jax.tree.map(
            self._axes_to_sharding, self._state_axes(), is_leaf=is_axes_leaf
        )

    def init_state(self, key) -> TrainState:
        """Initialize params directly INTO their shardings (jit with output
        shardings — each device materializes only its shard; no host-side
        full copy, required for 70B-scale)."""
        return jax.jit(
            self._make_state_fn, out_shardings=self.state_shardings()
        )(key)

    # --- train step ---

    def _loss_fn(self, params, batch, segment_ids=None):
        if self.loss_fn is not None:
            return self.loss_fn(self.model_cfg, params, batch)
        inputs = batch[:, :-1]
        targets = batch[:, 1:]
        mask = (targets != -1).astype(jnp.float32)
        if self.family is not llama:
            logits = self.family.forward(
                self.model_cfg, params, inputs, segment_ids=segment_ids,
                attn_impl=self.attn_impl)
            return llama.cross_entropy_loss(
                logits, jnp.maximum(targets, 0), mask=mask)
        if self.cfg.fused_loss:
            hidden = llama.forward_hidden(
                self.model_cfg, params, inputs, segment_ids=segment_ids,
                attn_impl=self.attn_impl, mesh=self.mesh,
                sp_axis=self.sp_axis)
            return llama.fused_cross_entropy(
                self.model_cfg, params, hidden, targets, mask=mask,
                chunk=self.cfg.loss_chunk)
        logits = llama.forward(self.model_cfg, params, inputs,
                               segment_ids=segment_ids,
                               attn_impl=self.attn_impl,
                               mesh=self.mesh, sp_axis=self.sp_axis)
        loss = llama.cross_entropy_loss(
            logits, jnp.maximum(targets, 0), mask=mask
        )
        return loss

    def _pp_loss_and_grad(self, params, batch):
        """1F1B pipelined loss + grads (pipeline_value_and_grad implements
        the backward itself — this is NOT differentiated through)."""
        from ray_tpu.ops.rope import rope_sin_cos
        from ray_tpu.parallel.pipeline import (
            make_llama_head_fn,
            make_llama_stage_fn,
            pipeline_value_and_grad,
            split_stages,
        )

        cfg = self.model_cfg
        n_pp = self.mesh.shape[self.pp_axis]
        m = self.cfg.n_microbatches or n_pp
        inputs = batch[:, :-1]
        targets = batch[:, 1:]
        mask = (targets != -1).astype(jnp.float32)
        b, s = inputs.shape
        if b % m:
            raise ValueError(f"batch {b} not divisible by {m} microbatches")

        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)
        stage_fn = make_llama_stage_fn(cfg, sin, cos, self.attn_impl)
        head_fn = make_llama_head_fn(cfg)
        # io params: embedding (stage-0 lookup, + head when tied), final
        # norm + head (last stage). The schedule accumulates ALL their grad
        # contributions into one d_io — tied embeddings need no fixup.
        io_params = {k: v for k, v in params.items() if k != "blocks"}

        def embed_fn(io, tok):
            return io["embedding"][tok]

        mb = b // m
        (loss_sum, weight_sum), (d_sp, d_io, _) = pipeline_value_and_grad(
            stage_fn, head_fn,
            split_stages(params["blocks"], n_pp), io_params,
            inputs.reshape(m, mb, s),
            targets.reshape(m, mb, s),
            mask.reshape(m, mb, s),
            mesh=self.mesh, axis=self.pp_axis,
            embed_fn=embed_fn,
        )
        weight = jnp.maximum(weight_sum, 1.0)
        grads = dict(
            d_io,
            blocks=jax.tree.map(
                lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]),
                d_sp),
        )
        # grads are of loss_sum; mean-loss grads = grads / Σmask
        grads = jax.tree.map(
            lambda g, p: (g / weight).astype(p.dtype), grads, params)
        return loss_sum / weight, grads

    def _step(self, state: TrainState, batch):
        if self.pp_axis:
            loss, grads = self._pp_loss_and_grad(state.params, batch)
        else:
            loss, grads = jax.value_and_grad(self._loss_fn)(
                state.params, batch)
        updates, new_opt = self.optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        metrics = {"loss": loss, "grad_norm": gnorm, "step": new_state.step}
        return new_state, metrics

    def _batch_shardings(self, batch):
        """Per-leaf data sharding: dim 0 is the batch axis, the rest
        replicated — so dict batches may mix ranks (e.g. [B, S] tokens
        with [B] labels)."""
        def leaf(x):
            nd = int(getattr(x, "ndim", 0))
            if nd == 0:   # python scalars / 0-d arrays: replicate
                return NamedSharding(self.mesh, P())
            return batch_sharding(self.mesh, self.rules, ndim=nd,
                                  shard_seq=False)

        return jax.tree.map(leaf, batch)

    def compile_step(self, state: TrainState, batch):
        # keyed on the batch pytree structure + leaf ranks: a later
        # batch with a different structure gets its own jit rather than
        # hitting stale in_shardings
        key = (jax.tree.structure(batch),
               tuple(int(getattr(x, "ndim", 0))
                     for x in jax.tree.leaves(batch)))
        step = self._jit_step.get(key)
        if step is None:
            donate = (0,) if self.cfg.donate_state else ()
            step = jax.jit(
                self._step,
                # state keeps its shardings
                in_shardings=(None, self._batch_shardings(batch)),
                donate_argnums=donate,
            )
            self._jit_step[key] = step
        return step

    def train_step(self, state: TrainState, batch):
        """One SPMD optimization step. ``batch``: int32 [B, S+1] tokens
        (last column is the shifted target; -1 = padding), or — with a
        custom ``loss_fn`` — any pytree whose leaves lead with the
        batch dim."""
        step_fn = self.compile_step(state, batch)
        batch = jax.device_put(batch, self._batch_shardings(batch))
        return step_fn(state, batch)

    # --- simple fit loop (full harness arrives with the trial controller) ---

    def fit(self, state: TrainState, data_iter, *, steps: int,
            log_every: int = 10, callback: Callable | None = None):
        history = []
        t0 = time.perf_counter()
        for i in range(steps):
            batch = next(data_iter)
            state, metrics = self.train_step(state, batch)
            if (i + 1) % log_every == 0 or i == steps - 1:
                m = {k: float(v) for k, v in metrics.items()}
                m["steps_per_s"] = (i + 1) / (time.perf_counter() - t0)
                history.append(m)
                if callback:
                    callback(m)
        return state, history
