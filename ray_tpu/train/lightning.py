"""LightningTrainer: PyTorch-Lightning modules inside the rank-actor
harness.

Reference analog: ``train/lightning/lightning_trainer.py:241`` — the
reference wraps a ``LightningModule`` + trainer config and runs
``pl.Trainer.fit`` on every rank worker over the torch process group.

Two execution paths, same contract:

- **pytorch_lightning installed**: the user's module runs under a real
  ``pl.Trainer`` (one device per rank; Lightning's DDP picks up the
  torch.distributed env the torch backend exports), with a callback
  bridging per-epoch metrics into ``session.report``.
- **not installed** (this image): a built-in LOOP ADAPTER drives any
  object conforming to the LightningModule protocol —
  ``training_step(batch, batch_idx)`` → loss, ``configure_optimizers()``,
  ``train_dataloader()``, optional ``validation_step`` /
  ``val_dataloader`` / ``on_train_epoch_end`` — with gradient averaging
  over the gloo group (the DDP the reference's strategy provides) and
  the same per-epoch reports. The protocol, not the import, is the
  integration surface.

Checkpoint bridge: rank 0 saves ``state_dict()`` per epoch into the
trial dir and attaches it to the report (``train/lightning``'s
RayModelCheckpoint analog), so Tune/AIR restore works unchanged.
"""

from __future__ import annotations

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train import session
from ray_tpu.train.torch import TorchConfig, TorchTrainer


def _has_lightning():
    try:
        import pytorch_lightning  # noqa: F401

        return True
    except ImportError:
        return False


def _wrap_lightning(module_init_per_worker, trainer_kwargs: dict):
    max_epochs = int(trainer_kwargs.get("max_epochs", 1))
    max_steps = trainer_kwargs.get("max_steps")

    def lightning_loop(config):
        import torch

        module = module_init_per_worker(config)
        for attr in ("training_step", "configure_optimizers",
                     "train_dataloader"):
            if not callable(getattr(module, attr, None)):
                raise TypeError(
                    f"module must follow the LightningModule protocol; "
                    f"missing {attr}()")
        if _has_lightning():
            _fit_with_pl(module, trainer_kwargs)
            return
        # ---- built-in loop adapter (no lightning in the image) ----
        ctx = session.get_context()
        world = ctx.get_world_size()
        optimizers = module.configure_optimizers()
        if isinstance(optimizers, (list, tuple)):
            optimizers = list(optimizers)
            if optimizers and isinstance(optimizers[0], (list, tuple)):
                optimizers = list(optimizers[0])   # ([opts], [scheds])
        else:
            optimizers = [optimizers]
        step = 0
        for epoch in range(max_epochs):
            if callable(getattr(module, "on_train_epoch_start", None)):
                module.on_train_epoch_start()
            losses = []
            for batch_idx, batch in enumerate(module.train_dataloader()):
                for opt in optimizers:
                    opt.zero_grad()
                loss = module.training_step(batch, batch_idx)
                if isinstance(loss, dict):
                    loss = loss["loss"]
                loss.backward()
                if world > 1:
                    # DDP gradient averaging over the gloo group the
                    # torch backend initialized (reference: Lightning's
                    # ddp strategy does exactly this inside pl)
                    for p in module.parameters():
                        if p.grad is not None:
                            torch.distributed.all_reduce(p.grad)
                            p.grad /= world
                for opt in optimizers:
                    opt.step()
                losses.append(float(loss.detach()))
                step += 1
                if max_steps is not None and step >= max_steps:
                    break
            if callable(getattr(module, "on_train_epoch_end", None)):
                module.on_train_epoch_end()
            val_loss = _run_validation(module)
            metrics = {"epoch": epoch, "step": step,
                       "train_loss": (sum(losses) / len(losses)
                                      if losses else 0.0)}
            if val_loss is not None:
                metrics["val_loss"] = val_loss
            ckpt_dir = _save_checkpoint(module, ctx, epoch)
            session.report(metrics, checkpoint_dir=ckpt_dir)
            if max_steps is not None and step >= max_steps:
                break

    return lightning_loop


def _run_validation(module):
    if not callable(getattr(module, "validation_step", None)) or \
            not callable(getattr(module, "val_dataloader", None)):
        return None
    import torch

    vals = []
    with torch.no_grad():
        for i, batch in enumerate(module.val_dataloader()):
            out = module.validation_step(batch, i)
            if isinstance(out, dict):
                out = out.get("val_loss", out.get("loss"))
            if out is not None:
                vals.append(float(out))
    return sum(vals) / len(vals) if vals else None


def _save_checkpoint(module, ctx, epoch: int):
    if ctx.get_world_rank() != 0:
        return None
    import os

    import torch

    ckpt_dir = os.path.join(ctx.get_trial_dir(), f"lightning_ep{epoch}")
    os.makedirs(ckpt_dir, exist_ok=True)
    torch.save({"state_dict": module.state_dict(), "epoch": epoch},
               os.path.join(ckpt_dir, "checkpoint.pt"))
    return ckpt_dir


def _fit_with_pl(module, trainer_kwargs: dict):
    import pytorch_lightning as pl

    class _ReportCallback(pl.Callback):
        def on_train_epoch_end(self, trainer, pl_module):
            metrics = {k: float(v) for k, v in
                       trainer.callback_metrics.items()}
            metrics["epoch"] = trainer.current_epoch
            session.report(metrics)

    kwargs = dict(trainer_kwargs)
    kwargs.setdefault("enable_progress_bar", False)
    kwargs.setdefault("logger", False)
    callbacks = list(kwargs.pop("callbacks", []))
    callbacks.append(_ReportCallback())
    trainer = pl.Trainer(callbacks=callbacks, **kwargs)
    trainer.fit(module)


class LightningTrainer(TorchTrainer):
    """Run a LightningModule(-protocol) training loop on every rank.

    Usage::

        class Model(torch.nn.Module):     # or pl.LightningModule
            def training_step(self, batch, i): ...
            def configure_optimizers(self): ...
            def train_dataloader(self): ...

        result = LightningTrainer(
            lambda cfg: Model(),
            trainer_kwargs={"max_epochs": 2},
            scaling_config=ScalingConfig(num_workers=2),
        ).fit()
    """

    def __init__(self, module_init_per_worker, *,
                 trainer_kwargs: dict | None = None,
                 train_loop_config: dict | None = None,
                 torch_config: TorchConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None):
        super().__init__(
            _wrap_lightning(module_init_per_worker, trainer_kwargs or {}),
            train_loop_config=train_loop_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
