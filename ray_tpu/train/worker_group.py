"""WorkerGroup + BackendExecutor: rank actors for SPMD training.

Reference analog: ``python/ray/train/_internal/worker_group.py``
(``WorkerGroup:102``) and ``backend_executor.py`` (``BackendExecutor:66``,
``start:125``, ``start_training:424``). The reference's backend hook runs
``torch.distributed.init_process_group`` on every rank
(``train/torch/config.py:63``); the TPU-native analog wires each rank for
``jax.distributed.initialize`` — coordinator address published through the
GCS KV (replacing torch's TCP store rendezvous). On a single host the
ranks share one process group trivially and the mesh is per-rank local.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _init_session


@ray_tpu.remote
class _RankWorker:
    """One rank of the SPMD group (reference: per-rank train worker actor).
    """

    def __init__(self, rank: int, world_size: int, coordinator: str | None,
                 env: dict | None = None):
        self.rank = rank
        self.world_size = world_size
        self.coordinator = coordinator
        for k, v in (env or {}).items():
            os.environ[k] = str(v)
        # multi-host TPU bootstrap (jax.distributed): only when a
        # coordinator is published AND this process owns TPU chips
        if coordinator and os.environ.get("JAX_PLATFORMS", "") not in (
                "cpu", "cpu,"):
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size, process_id=rank)

    def run(self, fn_blob_or_fn, config: dict, bus, trial_dir: str,
            restore_checkpoint: str | None = None, run_name: str = ""):
        import cloudpickle

        from ray_tpu.train import session as _session_mod

        fn = (cloudpickle.loads(fn_blob_or_fn)
              if isinstance(fn_blob_or_fn, bytes) else fn_blob_or_fn)
        ctx = TrainContext(rank=self.rank, world_size=self.world_size,
                           local_rank=self.rank, trial_dir=trial_dir,
                           experiment_name=run_name,
                           restore_checkpoint=restore_checkpoint)
        _init_session(ctx, bus)
        # trainer-config FLOPs declaration (the alternative to calling
        # session.set_flops_per_step() inside the loop)
        if isinstance(config, dict) and config.get("flops_per_step"):
            _session_mod.set_flops_per_step(
                config["flops_per_step"], config.get("peak_flops"))
        try:
            try:
                result = fn(config) if _wants_config(fn) else fn()
            finally:
                t = _session_mod.telemetry()
                if t is not None:
                    t.close()
        except BaseException as e:  # noqa: BLE001
            import traceback

            ray_tpu.get(bus.mark_done.remote(
                self.rank, error=f"{type(e).__name__}: {e}\n"
                                 f"{traceback.format_exc()}"))
            raise
        ray_tpu.get(bus.mark_done.remote(self.rank))
        return result

    def execute(self, fn, *args, **kwargs):
        return fn(*args, **kwargs)

    def ping(self):
        return self.rank


def _wants_config(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return True


class WorkerGroup:
    """N rank actors created per ScalingConfig (placement-group backed in
    the reference; resource demands express the same constraint here)."""

    def __init__(self, scaling: ScalingConfig, env: dict | None = None):
        self.scaling = scaling
        n = scaling.num_workers
        res = scaling.worker_resources()
        coordinator = None  # single-host: no jax.distributed rendezvous
        self.workers = [
            _RankWorker.options(
                num_cpus=res.get("CPU", 1),
                num_tpus=res.get("TPU") or None,
                resources={k: v for k, v in res.items()
                           if k not in ("CPU", "TPU")} or None,
            ).remote(rank, n, coordinator, env)
            for rank in range(n)
        ]

    def execute_async(self, fn, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn, *args, **kwargs):
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def healthy(self) -> bool:
        try:
            ray_tpu.get([w.ping.remote() for w in self.workers], timeout=10)
            return True
        except Exception:  # noqa: BLE001
            return False

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


class BackendExecutor:
    """Launches the user training loop on all ranks and streams reports
    (reference: BackendExecutor.start_training:424)."""

    def __init__(self, scaling: ScalingConfig, env: dict | None = None):
        self.scaling = scaling
        self.group = WorkerGroup(scaling, env=env)
        from ray_tpu.train.session import _ReportBus

        self.bus = _ReportBus.remote(scaling.num_workers)

    def start_training(self, train_fn: Callable, config: dict,
                       trial_dir: str,
                       restore_checkpoint: str | None = None,
                       run_name: str = "") -> list:
        import cloudpickle

        blob = cloudpickle.dumps(train_fn, protocol=5)
        return [w.run.remote(blob, config, self.bus, trial_dir,
                             restore_checkpoint, run_name)
                for w in self.group.workers]

    def poll_reports(self) -> tuple[list, bool]:
        return ray_tpu.get(self.bus.drain.remote())

    def shutdown(self):
        self.group.shutdown()
        try:
            ray_tpu.kill(self.bus)
        except Exception:  # noqa: BLE001
            pass
