"""Training telemetry: step-time decomposition, MFU, goodput buckets.

Reference analog: Ray Train's run/worker state tracking
(``python/ray/train/_internal/state/``) plus the goodput accounting the
reference leaves to external tools (TensorBoard profiles / cloud
goodput exporters). Here both ride the in-repo observability planes:
per-step series go out through the per-worker MetricsPusher (metrics
plane, PR 4), each step is a span under the run's trace (tracing plane,
PR 6), and cumulative run progress piggybacks on metric frames as an
annex so ``util.state.train_goodput`` / ``train_stragglers`` can answer
even after the windowed series expire.

One :class:`StepTelemetry` lives per rank session (created by
``session._init_session``). The contract with the training loop:

- ``session.timeit("data_wait")`` / ``"collective_sync"`` /
  ``"checkpoint"`` / ``"compute"`` context managers accumulate measured
  wall clock into the CURRENT step's buckets.
- ``session.report(...)`` closes the step: step wall = time since the
  previous report (or since the first instrumented activity, for step
  1). Whatever the explicit buckets did not cover is the residual —
  attributed to ``compile`` on the first step (jit tracing +
  compilation happen inside the first ``train_step``) and ``compute``
  afterwards. The decomposition therefore sums to the observed step
  wall BY CONSTRUCTION; the bench asserts it anyway.

Goodput buckets (cumulative, per rank):

- ``init``       session start -> first instrumented activity
- ``compile``    first-step residual
- ``productive`` per-step compute
- ``checkpoint`` save/restore wall inside steps
- ``stall``      data_wait + collective_sync
- ``restart``    elastic reform / trainer retry gaps (driver-recorded
                 via :func:`record_run_bucket`)

goodput_fraction = productive / total.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
import uuid

GOODPUT_BUCKETS = ("init", "compile", "productive", "checkpoint",
                   "stall", "restart")
STEP_STAGES = ("data_wait", "compute", "collective_sync", "checkpoint",
               "compile")
# step stage -> goodput bucket
_STAGE_TO_BUCKET = {"data_wait": "stall", "collective_sync": "stall",
                    "compute": "productive", "checkpoint": "checkpoint",
                    "compile": "compile"}

ANNEX_PREFIX = "train/progress/"

# peak dense-matmul TFLOPs per chip (bf16) — same table the bench uses;
# MFU needs a peak, declared or detected
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5litepod": 197.0,
                "v5p": 459.0, "v6e": 918.0}


def _enabled() -> bool:
    try:
        from ray_tpu.utils.config import get_config

        return bool(get_config().train_telemetry_enabled)
    except Exception:  # noqa: BLE001 - config unavailable during boot
        return True


def run_trace_id(run: str) -> str:
    """Deterministic trace id for a run: every rank's step spans land in
    the SAME trace without any rendezvous."""
    return hashlib.sha1(f"train:{run}".encode()).hexdigest()[:16]


def detect_peak_flops() -> float | None:
    """Per-chip peak FLOP/s from the local jax device kind, if it is a
    TPU generation the table knows. None on CPU/GPU — callers must
    declare a peak for MFU there."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 - no jax / no devices
        return None
    for key, tflops in _PEAK_TFLOPS.items():
        if key in kind:
            return tflops * 1e12
    return None


class StepTelemetry:
    """Per-rank step clock: bucket accumulation, residual attribution,
    MFU, goodput counters, progress annex, step spans, and the
    watchdog's in-flight token for the currently-running step."""

    def __init__(self, run: str, rank: int, *, world_size: int = 1,
                 flops_per_step: float | None = None,
                 peak_flops: float | None = None,
                 history_cap: int = 4096):
        self.run = run or "default"
        self.rank = int(rank)
        self.world_size = world_size
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        self.step = 0
        self.history: list[dict] = []
        self._history_cap = history_cap
        self._created = time.monotonic()
        self._step_start: float | None = None
        self._buckets: dict[str, float] = {}
        self.goodput: dict[str, float] = {b: 0.0 for b in GOODPUT_BUCKETS}
        self._last_annex = 0.0
        self._inflight_token: int | None = None
        self._closed = False
        self._lock = threading.Lock()
        self._metrics = None   # lazily-built metric handles

    # -- declaration ---------------------------------------------------

    def set_flops_per_step(self, flops: float,
                           peak_flops: float | None = None) -> None:
        self.flops_per_step = float(flops)
        if peak_flops is not None:
            self.peak_flops = float(peak_flops)

    # -- bucket accumulation -------------------------------------------

    @contextlib.contextmanager
    def timeit(self, bucket: str):
        """Accumulate the block's wall clock into ``bucket`` for the
        current step. First use also marks the step start (pre-step
        time becomes the ``init`` goodput bucket)."""
        self._ensure_step_start()
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                self._buckets[bucket] = self._buckets.get(bucket, 0.0) + dt

    def mark_gap(self) -> None:
        """Restart the step clock at 'now', discarding the wall clock
        since the last report — for out-of-band gaps (elastic reform,
        retry pauses) that are already accounted to a bucket via
        :func:`record_run_bucket` and must not leak into the next step's
        residual."""
        if self._step_start is not None:
            self._step_start = time.monotonic()

    def _ensure_step_start(self) -> float:
        if self._step_start is None:
            now = time.monotonic()
            self._step_start = now
            self.goodput["init"] += now - self._created
            self._watchdog_begin()
        return self._step_start

    # -- step close (called from session.report) ----------------------

    def on_report(self, metrics: dict | None = None) -> dict:
        """Close the current step; returns the stamp dict
        ``{step, wall_s, stages, mfu}``. ``stages`` sums to ``wall_s``
        exactly (residual attribution)."""
        start = self._ensure_step_start()
        now = time.monotonic()
        wall = max(now - start, 0.0)
        with self._lock:
            stages = dict(self._buckets)
            self._buckets = {}
        explicit = sum(stages.values())
        residual = max(wall - explicit, 0.0)
        sink = "compile" if self.step == 0 else "compute"
        stages[sink] = stages.get(sink, 0.0) + residual
        self.step += 1
        mfu = None
        if self.flops_per_step and self.peak_flops and wall > 0:
            mfu = self.flops_per_step / wall / self.peak_flops
        stamp = {"step": self.step, "wall_s": wall, "stages": stages,
                 "mfu": mfu}
        if len(self.history) < self._history_cap:
            self.history.append(stamp)
        for stage, dt in stages.items():
            self.goodput[_STAGE_TO_BUCKET.get(stage, "productive")] += dt
        self._emit_metrics(stamp)
        self._emit_span(stamp, start_mono=start)
        self._publish_annex(stamp)
        # the watchdog token rolls over: this step finished, the next
        # one is now in flight (close() retires the dangling token)
        self._watchdog_end()
        self._step_start = now
        self._watchdog_begin()
        return stamp

    # -- emission ------------------------------------------------------

    def _metric_handles(self):
        if self._metrics is None:
            from ray_tpu.util import metrics as _m

            self._metrics = {
                "step_s": _m.histogram(
                    "train.step_s", "Training step wall clock (s)",
                    tag_keys=("run", "rank")),
                "stage_s": _m.histogram(
                    "train.step_stage_s",
                    "Per-stage step decomposition (s)",
                    tag_keys=("run", "rank", "stage")),
                "mfu": _m.gauge(
                    "train.mfu", "Model FLOPs utilization (0..1)",
                    tag_keys=("run", "rank")),
                "steps": _m.counter(
                    "train.steps_total", "Training steps completed",
                    tag_keys=("run", "rank")),
                "goodput": _m.counter(
                    "train.goodput_s",
                    "Run wall clock attributed per goodput bucket (s)",
                    tag_keys=("run", "rank", "bucket")),
            }
        return self._metrics

    def _emit_metrics(self, stamp: dict) -> None:
        from ray_tpu.util import metrics as _m

        if not (_m.enabled() and _enabled()):
            return
        h = self._metric_handles()
        tags = {"run": self.run, "rank": str(self.rank)}
        h["step_s"].observe(stamp["wall_s"], tags)
        h["steps"].inc(1, tags)
        for stage, dt in stamp["stages"].items():
            h["stage_s"].observe(dt, {**tags, "stage": stage})
        if stamp["mfu"] is not None:
            h["mfu"].set(stamp["mfu"], tags)
        for bucket, dt in stamp["stages"].items():
            h["goodput"].inc(dt, {**tags,
                                  "bucket": _STAGE_TO_BUCKET.get(
                                      bucket, "productive")})

    def _emit_span(self, stamp: dict, *, start_mono: float) -> None:
        from ray_tpu.util import tracing as _t

        if not _t.is_enabled():
            return
        wall_start = time.time() - (time.monotonic() - start_mono)
        parent = _t.SpanContext(trace_id=run_trace_id(self.run),
                                span_id=uuid.uuid4().hex[:16])
        step_ctx = _t.emit(
            "train.step", start=wall_start, duration=stamp["wall_s"],
            parent=parent, kind="train",
            attrs={"run": self.run, "rank": self.rank,
                   "step": stamp["step"], "mfu": stamp["mfu"]})
        offset = wall_start
        for stage, dt in sorted(stamp["stages"].items()):
            if dt <= 0:
                continue
            _t.emit(f"train.step.{stage}", start=offset, duration=dt,
                    parent=step_ctx, kind="train",
                    attrs={"run": self.run, "rank": self.rank,
                           "stage": stage})
            offset += dt

    def _publish_annex(self, stamp: dict, force: bool = False) -> None:
        if not _enabled():
            return
        now = time.monotonic()
        try:
            from ray_tpu.utils.config import get_config

            interval = float(get_config().train_progress_interval_s)
        except Exception:  # noqa: BLE001
            interval = 0.5
        if not force and now - self._last_annex < interval:
            return
        self._last_annex = now
        from ray_tpu.runtime import metrics_plane as _mp

        _mp.set_annex(
            f"{ANNEX_PREFIX}{self.run}/{self.rank}",
            {"run": self.run, "rank": self.rank, "step": self.step,
             "ts": time.time(), "step_s": stamp["wall_s"],
             "goodput": dict(self.goodput)})

    # -- watchdog ------------------------------------------------------

    def _watchdog_begin(self) -> None:
        from ray_tpu.util import tracing as _t

        self._inflight_token = _t.call_started(
            "train_step", f"{self.run}:rank{self.rank}:step{self.step + 1}")

    def _watchdog_end(self) -> None:
        from ray_tpu.util import tracing as _t

        _t.call_finished(self._inflight_token)
        self._inflight_token = None

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        """Retire the in-flight token and force a final annex publish so
        the last step/goodput totals are visible cluster-wide."""
        if self._closed:
            return
        self._closed = True
        self._watchdog_end()
        if self.step > 0 or any(v > 0 for v in self.goodput.values()):
            last = self.history[-1] if self.history else \
                {"wall_s": 0.0}
            self._publish_annex(last, force=True)


# ---------------------------------------------------------------------
# driver-side bucket recording (restart badput: trainer retries and
# elastic reforms happen OUTSIDE any rank session)

_driver_goodput: dict[tuple[str, str], dict[str, float]] = {}
_driver_lock = threading.Lock()


def record_run_bucket(run: str, bucket: str, seconds: float,
                      *, rank: str = "driver") -> None:
    """Attribute ``seconds`` of a run's wall clock to a goodput bucket
    from outside a rank session (DataParallelTrainer retry gaps,
    ElasticTrainer reforms). Rides the same counter + annex paths as
    per-step accounting so ``train_goodput`` sees one merged picture."""
    if seconds <= 0 or not _enabled():
        return
    run = run or "default"
    with _driver_lock:
        cum = _driver_goodput.setdefault(
            (run, rank), {b: 0.0 for b in GOODPUT_BUCKETS})
        cum[bucket] = cum.get(bucket, 0.0) + seconds
        snapshot = dict(cum)
    from ray_tpu.util import metrics as _m

    if _m.enabled():
        _m.counter("train.goodput_s",
                   "Run wall clock attributed per goodput bucket (s)",
                   tag_keys=("run", "rank", "bucket")).inc(
            seconds, {"run": run, "rank": rank, "bucket": bucket})
    from ray_tpu.runtime import metrics_plane as _mp

    _mp.set_annex(f"{ANNEX_PREFIX}{run}/{rank}",
                  {"run": run, "rank": rank, "step": 0,
                   "ts": time.time(), "step_s": 0.0,
                   "goodput": snapshot})
