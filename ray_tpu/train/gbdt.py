"""Distributed gradient-boosted decision trees — native implementation.

Reference analog: ``python/ray/train/gbdt_trainer.py`` +
``train/xgboost/xgboost_trainer.py`` + ``train/lightgbm/lightgbm_trainer.py``.
The reference wraps external libraries (xgboost_ray / lightgbm_ray) whose
distributed mode sums per-feature gradient histograms over rabit AllReduce.
This module implements the same distributed algorithm natively — no
xgboost/lightgbm dependency:

- Features are quantile-binned to uint8 once (the standard "hist" method).
- Worker actors each hold a row shard; every boosting round they compute
  local (grad, hess) from the objective and, per tree level, vectorized
  per-node × per-feature × per-bin histograms (one ``np.bincount`` over
  fused keys — the hot op, linear in shard rows).
- The driver sums the workers' histograms (the AllReduce step, carried on
  the object plane), picks best splits with the exact xgboost gain
  formula, and broadcasts the split frontier; workers re-partition rows
  locally. No row ever leaves its shard — only O(nodes × features × bins)
  histograms move.
- Histogram accumulators are float64, so an N-worker run produces
  bit-identical trees to a 1-worker run (tested); determinism is a
  correctness check the wrapped-library reference cannot make.

``XGBoostTrainer`` grows depth-wise to ``max_depth`` (xgboost's default
policy); ``LightGBMTrainer`` grows leaf-wise best-first to ``num_leaves``
(lightgbm's policy). Both accept their library's core param names.
"""

from __future__ import annotations

import math
import os
import pickle
import time
from dataclasses import dataclass, field

import numpy as np

import ray_tpu
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import Result

MAX_BINS = 256


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


@dataclass
class _Tree:
    """One regression tree over BINNED features, stored as flat arrays.
    ``feature[i] < 0`` marks a leaf; internal nodes send
    ``bin <= threshold`` left."""

    feature: np.ndarray      # int32 [n_nodes]
    threshold: np.ndarray    # int32 [n_nodes] (bin index)
    left: np.ndarray         # int32 [n_nodes]
    right: np.ndarray        # int32 [n_nodes]
    value: np.ndarray        # float32 [n_nodes] (leaf weight * eta)

    def predict_binned(self, binned: np.ndarray) -> np.ndarray:
        node = np.zeros(len(binned), dtype=np.int32)
        # vectorized level-order descent: all rows step together until
        # every row sits on a leaf (bounded by tree height)
        while True:
            feat = self.feature[node]
            active = feat >= 0
            if not active.any():
                return self.value[node]
            rows = np.nonzero(active)[0]
            f = feat[rows]
            go_left = binned[rows, f] <= self.threshold[node[rows]]
            node[rows] = np.where(go_left, self.left[node[rows]],
                                  self.right[node[rows]])


@dataclass
class GBTModel:
    """A trained boosted ensemble + the bin edges to apply it to raw
    (un-binned) feature matrices."""

    trees: list = field(default_factory=list)
    bin_edges: list = field(default_factory=list)   # per-feature float64
    base_score: float = 0.0
    objective: str = "reg:squarederror"
    n_features: int = 0

    def bin(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        out = np.empty(X.shape, dtype=np.uint8)
        for j, edges in enumerate(self.bin_edges):
            out[:, j] = np.searchsorted(edges, X[:, j], side="left")
        return out

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        binned = self.bin(X)
        margin = np.full(len(binned), self.base_score, dtype=np.float64)
        for tree in self.trees:
            margin += tree.predict_binned(binned)
        return margin

    def predict(self, X: np.ndarray) -> np.ndarray:
        margin = self.predict_margin(X)
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-margin))
        return margin

    def save(self, path: str):
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(path: str) -> "GBTModel":
        with open(path, "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# objectives + metrics
# ---------------------------------------------------------------------------


def _grad_hess(objective: str, margin: np.ndarray, y: np.ndarray):
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-margin))
        return p - y, np.maximum(p * (1.0 - p), 1e-16)
    # reg:squarederror
    return margin - y, np.ones_like(margin)


def _eval_sums(objective: str, margin: np.ndarray, y: np.ndarray):
    """(sum, count) of the per-row loss terms — summable across shards."""
    if objective == "binary:logistic":
        p = np.clip(1.0 / (1.0 + np.exp(-margin)), 1e-12, 1 - 1e-12)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        err = ((p >= 0.5) != (y >= 0.5)).sum()
        return {"logloss": loss.sum(), "error": float(err), "n": len(y)}
    return {"se": ((margin - y) ** 2).sum(), "n": len(y)}


def _finish_metrics(objective: str, sums: dict, prefix: str) -> dict:
    n = max(sums.get("n", 0), 1)
    if objective == "binary:logistic":
        return {f"{prefix}-logloss": sums["logloss"] / n,
                f"{prefix}-error": sums["error"] / n}
    return {f"{prefix}-rmse": math.sqrt(sums["se"] / n)}


# ---------------------------------------------------------------------------
# split finding (driver side, on SUMMED histograms)
# ---------------------------------------------------------------------------


def _best_splits(hist_g: np.ndarray, hist_h: np.ndarray, *,
                 reg_lambda: float, gamma: float, min_child_weight: float):
    """Vectorized best split per node from summed histograms.

    ``hist_g/h``: float64 [n_nodes, n_features, n_bins]. Returns per-node
    (gain, feature, threshold_bin, g_left, h_left, g_total, h_total).
    Exact xgboost gain: 1/2 [GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)] − γ.
    """
    cg = np.cumsum(hist_g, axis=2)     # left sums for threshold = bin b
    ch = np.cumsum(hist_h, axis=2)
    g_tot = cg[:, :1, -1:]             # [n,1,1] (same across features)
    h_tot = ch[:, :1, -1:]
    gl, hl = cg[:, :, :-1], ch[:, :, :-1]   # can't send ALL rows left
    gr, hr = g_tot - gl, h_tot - hl
    ok = (hl >= min_child_weight) & (hr >= min_child_weight)
    parent = (g_tot ** 2) / (h_tot + reg_lambda)
    gain = 0.5 * ((gl ** 2) / (hl + reg_lambda)
                  + (gr ** 2) / (hr + reg_lambda) - parent) - gamma
    gain = np.where(ok, gain, -np.inf)
    flat = gain.reshape(gain.shape[0], -1)
    best = np.argmax(flat, axis=1)
    n_bins = gain.shape[2]
    feat, thresh = best // n_bins, best % n_bins
    idx = np.arange(gain.shape[0])
    return (flat[idx, best], feat.astype(np.int32),
            thresh.astype(np.int32), gl[idx, feat, thresh],
            hl[idx, feat, thresh], g_tot[:, 0, 0], h_tot[:, 0, 0])


def _leaf_value(g: float, h: float, reg_lambda: float, eta: float) -> float:
    return float(-g / (h + reg_lambda) * eta)


# ---------------------------------------------------------------------------
# the worker actor: holds a shard, serves histograms
# ---------------------------------------------------------------------------


class _GBDTShard:
    """Per-worker state. Runs inside a ray_tpu actor (class is wrapped
    with ``ray_tpu.remote`` at trainer start so importing this module
    never requires a live runtime)."""

    def __init__(self, binned: np.ndarray, y: np.ndarray, objective: str,
                 base_score: float):
        self.binned = np.ascontiguousarray(binned)
        self.y = np.asarray(y, dtype=np.float64)
        self.objective = objective
        self.margin = np.full(len(y), base_score, dtype=np.float64)
        self.n_features = binned.shape[1]
        # per-tree state
        self.node = np.zeros(len(y), dtype=np.int32)
        self.grad = np.zeros(len(y))
        self.hess = np.zeros(len(y))

    def start_tree(self):
        self.node[:] = 0
        self.grad, self.hess = _grad_hess(self.objective, self.margin,
                                          self.y)
        return True

    def histograms(self, node_ids: list[int]):
        """float64 [len(node_ids), F, MAX_BINS] grad + hess histograms
        over this shard's rows, via one fused-key bincount each."""
        n_nodes, F = len(node_ids), self.n_features
        remap = {nid: i for i, nid in enumerate(node_ids)}
        local = np.full(self.node.max(initial=0) + 1, -1, dtype=np.int32)
        for nid, i in remap.items():
            if nid < len(local):
                local[nid] = i
        mask = local[self.node] >= 0
        rows = np.nonzero(mask)[0]
        if len(rows) == 0:
            z = np.zeros((n_nodes, F, MAX_BINS))
            return z, z
        node_local = local[self.node[rows]].astype(np.int64)
        bins = self.binned[rows]            # [R, F] uint8
        # fused key: ((node_local * F) + feature) * MAX_BINS + bin
        base = (node_local[:, None] * F
                + np.arange(F, dtype=np.int64)[None, :]) * MAX_BINS
        keys = (base + bins).ravel()
        size = n_nodes * F * MAX_BINS
        g = np.bincount(keys, weights=np.repeat(self.grad[rows], F),
                        minlength=size)
        h = np.bincount(keys, weights=np.repeat(self.hess[rows], F),
                        minlength=size)
        return (g.reshape(n_nodes, F, MAX_BINS),
                h.reshape(n_nodes, F, MAX_BINS))

    def apply_splits(self, splits: list):
        """``splits``: (node_id, feature, threshold, left_id, right_id).
        Re-partition this shard's rows into the children."""
        for nid, feat, thresh, lid, rid in splits:
            rows = np.nonzero(self.node == nid)[0]
            if len(rows) == 0:
                continue
            go_left = self.binned[rows, feat] <= thresh
            self.node[rows] = np.where(go_left, lid, rid)
        return True

    def finish_tree(self, tree_arrays: tuple):
        """Fold the finished tree's leaf values into the margins using
        the node assignment built during growth (no re-descent)."""
        tree = _Tree(*map(np.asarray, tree_arrays))
        self.margin += tree.value[self.node]
        return True

    def eval_sums(self):
        return _eval_sums(self.objective, self.margin, self.y)


# ---------------------------------------------------------------------------
# trainers
# ---------------------------------------------------------------------------


class _GBDTTrainerBase:
    """Shared driver-side loop (reference: GBDTTrainer,
    ``train/gbdt_trainer.py``). Subclasses set the growth policy."""

    _growth = "depthwise"

    def __init__(self, *, params: dict | None = None,
                 label_column: str,
                 datasets: dict,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 num_boost_round: int = 10):
        self.params = dict(params or {})
        self.label_column = label_column
        self.datasets = datasets
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.num_boost_round = int(
            self.params.pop("num_boost_round", num_boost_round))

    # -- data ----------------------------------------------------------

    def _to_xy(self, ds) -> tuple[np.ndarray, np.ndarray]:
        """Accept a ray_tpu.data Dataset, a pandas DataFrame, or a dict
        of columns; return (X float64 [N,F], y float64 [N])."""
        if hasattr(ds, "iter_batches"):        # ray_tpu.data.Dataset
            cols: dict[str, list] = {}
            for batch in ds.iter_batches():
                for k, v in batch.items():
                    cols.setdefault(k, []).append(np.asarray(v))
            merged = {k: np.concatenate(v) for k, v in cols.items()}
        elif hasattr(ds, "columns"):           # pandas
            merged = {c: np.asarray(ds[c]) for c in ds.columns}
        else:                                  # dict of columns
            merged = {k: np.asarray(v) for k, v in ds.items()}
        y = np.asarray(merged.pop(self.label_column), dtype=np.float64)
        feats = sorted(merged)
        X = np.stack([np.asarray(merged[f], dtype=np.float64)
                      for f in feats], axis=1)
        return X, y

    @staticmethod
    def _quantile_edges(X: np.ndarray) -> list[np.ndarray]:
        """Per-feature bin edges from quantiles (255 cuts -> 256 bins),
        deduplicated so constant features collapse to one bin."""
        edges = []
        qs = np.linspace(0, 1, MAX_BINS)[1:]
        for j in range(X.shape[1]):
            e = np.unique(np.quantile(X[:, j], qs))
            edges.append(e)
        return edges

    # -- the boosting loop --------------------------------------------

    def fit(self) -> Result:
        objective = self.params.get("objective", "reg:squarederror")
        eta = float(self.params.get("eta",
                                    self.params.get("learning_rate", 0.3)))
        reg_lambda = float(self.params.get("lambda",
                                           self.params.get("reg_lambda",
                                                           1.0)))
        gamma = float(self.params.get("gamma", 0.0))
        mcw = float(self.params.get("min_child_weight", 1.0))
        max_depth = int(self.params.get("max_depth", 6))
        num_leaves = int(self.params.get("num_leaves", 31))

        X, y = self._to_xy(self.datasets["train"])
        base_score = float(self.params.get(
            "base_score",
            np.clip(y.mean(), 1e-6, 1 - 1e-6)
            if objective == "binary:logistic" else y.mean()))
        model = GBTModel(bin_edges=self._quantile_edges(X),
                         base_score=base_score, objective=objective,
                         n_features=X.shape[1])
        binned = model.bin(X)

        # shard rows across worker actors (reference: xgboost_ray
        # RayParams(num_actors=scaling.num_workers))
        n_workers = max(self.scaling.num_workers, 1)
        res = self.scaling.worker_resources()
        shard_cls = ray_tpu.remote(
            num_cpus=res.pop("CPU", 1), num_tpus=res.pop("TPU", None),
            resources=res or None)(_GBDTShard)
        bounds = np.linspace(0, len(y), n_workers + 1, dtype=np.int64)
        workers = [
            shard_cls.remote(binned[a:b], y[a:b], objective, base_score)
            for a, b in zip(bounds[:-1], bounds[1:]) if b > a]

        evals = {name: self._to_xy(ds)
                 for name, ds in self.datasets.items() if name != "train"}
        try:
            history = self._boost(workers, model, evals, objective,
                                  eta=eta, reg_lambda=reg_lambda,
                                  gamma=gamma, min_child_weight=mcw,
                                  max_depth=max_depth,
                                  num_leaves=num_leaves)
        finally:
            # release the shard actors' resources NOW (reference:
            # xgboost_ray shuts its training actors down after fit) — a
            # second trainer in the same session must not deadlock on
            # CPUs still held by a finished one
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass

        final = dict(history[-1]) if history else {}
        final["time_total_s"] = time.monotonic() - self._t0
        final["num_trees"] = len(model.trees)
        ckpt_dir = os.path.join(self.run_config.resolved_storage_path(),
                                f"gbdt_{int(time.time())}")
        os.makedirs(ckpt_dir, exist_ok=True)
        model.save(os.path.join(ckpt_dir, "model.pkl"))
        return Result(metrics=final, checkpoint_dir=ckpt_dir,
                      metrics_history=history)

    def _boost(self, workers, model, evals, objective, *, eta,
               reg_lambda, gamma, min_child_weight, max_depth,
               num_leaves) -> list[dict]:
        history = []
        self._t0 = time.monotonic()
        for _ in range(self.num_boost_round):
            ray_tpu.get([w.start_tree.remote() for w in workers])
            tree = self._grow_tree(
                workers, eta=eta, reg_lambda=reg_lambda, gamma=gamma,
                min_child_weight=min_child_weight, max_depth=max_depth,
                num_leaves=num_leaves)
            arrays = (tree.feature, tree.threshold, tree.left,
                      tree.right, tree.value)
            ray_tpu.get([w.finish_tree.remote(arrays) for w in workers])
            model.trees.append(tree)
            # distributed train metric: sum the shards' loss terms
            sums: dict[str, float] = {}
            for part in ray_tpu.get([w.eval_sums.remote()
                                     for w in workers]):
                for k, v in part.items():
                    sums[k] = sums.get(k, 0.0) + v
            metrics = _finish_metrics(objective, sums, "train")
            for name, (Xe, ye) in evals.items():
                margin = model.predict_margin(Xe)
                metrics.update(_finish_metrics(
                    objective, _eval_sums(objective, margin, ye), name))
            history.append(metrics)
        return history

    # -- growth policies ----------------------------------------------

    def _summed_hists(self, workers, frontier: list[int]):
        parts = ray_tpu.get([w.histograms.remote(frontier)
                             for w in workers])
        g = np.sum([p[0] for p in parts], axis=0)
        h = np.sum([p[1] for p in parts], axis=0)
        return g, h

    def _grow_tree(self, workers, *, eta, reg_lambda, gamma,
                   min_child_weight, max_depth, num_leaves) -> _Tree:
        feature, threshold = [-1], [0]
        left, right, value = [0], [0], [0.0]
        node_g, node_h = {0: None}, {0: None}   # filled from histograms

        def split_node(nid, feat, thresh, gl, hl, gt, ht):
            lid, rid = len(feature), len(feature) + 1
            feature[nid], threshold[nid] = int(feat), int(thresh)
            left[nid], right[nid] = lid, rid
            for _ in range(2):
                feature.append(-1)
                threshold.append(0)
                left.append(0)
                right.append(0)
                value.append(0.0)
            node_g[lid], node_h[lid] = gl, hl
            node_g[rid], node_h[rid] = gt - gl, ht - hl
            value[lid] = _leaf_value(gl, hl, reg_lambda, eta)
            value[rid] = _leaf_value(gt - gl, ht - hl, reg_lambda, eta)
            return lid, rid

        if self._growth == "depthwise":
            frontier = [0]
            for _depth in range(max_depth):
                if not frontier:
                    break
                hg, hh = self._summed_hists(workers, frontier)
                gains = _best_splits(hg, hh, reg_lambda=reg_lambda,
                                     gamma=gamma,
                                     min_child_weight=min_child_weight)
                splits, nxt = [], []
                for i, nid in enumerate(frontier):
                    gain = gains[0][i]
                    if not np.isfinite(gain) or gain <= 0:
                        if nid == 0:
                            # a single-leaf tree still shrinks the
                            # residual: the root gets its leaf weight
                            value[0] = _leaf_value(
                                gains[5][i], gains[6][i], reg_lambda,
                                eta)
                        continue
                    lid, rid = split_node(nid, gains[1][i], gains[2][i],
                                          gains[3][i], gains[4][i],
                                          gains[5][i], gains[6][i])
                    splits.append((nid, int(gains[1][i]),
                                   int(gains[2][i]), lid, rid))
                    nxt += [lid, rid]
                if splits:
                    ray_tpu.get([w.apply_splits.remote(splits)
                                 for w in workers])
                frontier = nxt
        else:   # leaf-wise best-first (lightgbm policy)
            import heapq

            heap: list = []   # (-gain, tiebreak, nid, split_tuple)
            n_leaves, tick = 1, 0

            def push(nids):
                """One fan-out/gather for ALL the given nodes (both
                children of a split share the round trip)."""
                nonlocal tick
                hg, hh = self._summed_hists(workers, nids)
                g = _best_splits(hg, hh, reg_lambda=reg_lambda,
                                 gamma=gamma,
                                 min_child_weight=min_child_weight)
                for i, nid in enumerate(nids):
                    if np.isfinite(g[0][i]) and g[0][i] > 0:
                        heapq.heappush(
                            heap, (-float(g[0][i]), tick, nid,
                                   tuple(x[i] for x in g)[1:]))
                        tick += 1
                    elif nid == 0:
                        # unsplittable root: single-leaf tree (see
                        # depthwise)
                        value[0] = _leaf_value(g[5][i], g[6][i],
                                               reg_lambda, eta)

            push([0])
            while heap and n_leaves < num_leaves:
                _, _, nid, (feat, thresh, gl, hl, gt, ht) = \
                    heapq.heappop(heap)
                lid, rid = split_node(nid, feat, thresh, gl, hl, gt, ht)
                ray_tpu.get([w.apply_splits.remote(
                    [(nid, int(feat), int(thresh), lid, rid)])
                    for w in workers])
                n_leaves += 1
                push([lid, rid])

        return _Tree(np.asarray(feature, dtype=np.int32),
                     np.asarray(threshold, dtype=np.int32),
                     np.asarray(left, dtype=np.int32),
                     np.asarray(right, dtype=np.int32),
                     np.asarray(value, dtype=np.float32))


class XGBoostTrainer(_GBDTTrainerBase):
    """Depth-wise histogram GBDT (reference:
    ``train/xgboost/xgboost_trainer.py``; same ``params`` names —
    objective/eta/max_depth/lambda/gamma/min_child_weight)."""

    _growth = "depthwise"


class LightGBMTrainer(_GBDTTrainerBase):
    """Leaf-wise best-first GBDT (reference:
    ``train/lightgbm/lightgbm_trainer.py``; honors ``num_leaves`` /
    ``learning_rate`` naming)."""

    _growth = "leafwise"
