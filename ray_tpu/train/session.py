"""Per-rank training session: report/checkpoint/context.

Reference analog: ``python/ray/train/_internal/session.py`` —
``_TrainSession`` (:110) with ``report`` (:399,659) streaming metrics +
checkpoints from rank workers back to the driver, and
``ray.train.get_context()`` exposing rank/world size.

The session is process-local state inside each rank actor; reports flow
through a shared ``_ReportBus`` actor the driver polls (the reference uses
an in-actor queue polled by the trainable)."""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any

import ray_tpu


@ray_tpu.remote
class _ReportBus:
    """Collects (rank, payload) reports; driver drains in arrival order."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.reports: list = []
        self.done_ranks: set = set()

    def push(self, rank: int, metrics: dict, checkpoint_dir=None):
        self.reports.append(
            {"rank": rank, "metrics": metrics, "checkpoint": checkpoint_dir})
        return len(self.reports)

    def mark_done(self, rank: int, error: str | None = None):
        self.done_ranks.add(rank)
        if error is not None:
            self.reports.append({"rank": rank, "error": error})
        return True

    def drain(self):
        out, self.reports = self.reports, []
        return out, len(self.done_ranks) >= self.world_size


@dataclass
class TrainContext:
    rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    trial_dir: str = ""
    experiment_name: str = ""
    restore_checkpoint: str | None = None

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


_session = threading.local()


def _init_session(context: TrainContext, bus=None):
    _session.context = context
    _session.bus = bus
    _session.iteration = 0
    from ray_tpu.train.telemetry import StepTelemetry, _enabled, \
        detect_peak_flops

    _session.telemetry = StepTelemetry(
        run=context.experiment_name, rank=context.rank,
        world_size=context.world_size,
        peak_flops=detect_peak_flops()) if _enabled() else None


def telemetry():
    """This rank's StepTelemetry (None outside a session or when
    ``train_telemetry_enabled`` is off)."""
    return getattr(_session, "telemetry", None)


def set_flops_per_step(flops: float, peak_flops: float | None = None):
    """Declare the model's FLOPs per optimizer step (and optionally the
    chip's peak FLOP/s — auto-detected on TPU) so every report carries
    MFU. The usual declaration is ``6 * n_params * tokens_per_step``."""
    t = telemetry()
    if t is not None:
        t.set_flops_per_step(flops, peak_flops)


def timeit(bucket: str):
    """Context manager attributing the block's wall clock to one step
    stage (``data_wait`` / ``compute`` / ``collective_sync`` /
    ``checkpoint``). No-op outside a session."""
    t = telemetry()
    if t is None:
        import contextlib

        return contextlib.nullcontext()
    return t.timeit(bucket)


def get_context() -> TrainContext:
    ctx = getattr(_session, "context", None)
    if ctx is None:
        return TrainContext()  # outside a worker: defaults (like reference)
    return ctx


def report(metrics: dict, *, checkpoint_dir: str | None = None):
    """Stream metrics (and optionally a checkpoint directory) to the
    driver. Rank 0's checkpoint is the one retained (reference: rank-0
    upload via StorageContext)."""
    ctx = get_context()
    bus = getattr(_session, "bus", None)
    _session.iteration = getattr(_session, "iteration", 0) + 1
    # close the telemetry step BEFORE the bus round trip: the push is
    # reporting overhead, booked into the NEXT step's wall (residual ->
    # compute), never into the step being stamped
    t = telemetry()
    if t is not None:
        t.on_report(metrics)
    if bus is not None:
        ray_tpu.get(bus.push.remote(ctx.rank, dict(metrics), checkpoint_dir))


def get_checkpoint_dir() -> str | None:
    """Restore path for resumed runs: per-trial context first (set by the
    controller on restore / PBT exploit), env var as the out-of-band
    fallback."""
    ctx = get_context()
    if ctx.restore_checkpoint:
        return ctx.restore_checkpoint
    return os.environ.get("RAY_TPU_RESTORE_CHECKPOINT") or None
