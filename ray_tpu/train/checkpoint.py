"""Checkpointing: async, sharded, top-k retention.

Analog of the reference Train's ``Checkpoint`` + ``StorageContext`` +
``CheckpointManager`` (``train/_checkpoint.py:55``,
``train/_internal/storage.py:350``, ``train/_internal/checkpoint_manager.py``)
rebuilt on Orbax/tensorstore: every device writes only its own shards
(OCDBT), saves are async (training continues during the write), and restore
places shards directly onto the target mesh via the sharding pytree.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, *, metrics: dict | None = None,
             force: bool = False) -> bool:
        return self._mgr.save(
            step,
            args=ocp.args.StandardSave(state),
            metrics=metrics,
            force=force,
        )

    def restore(self, step: int | None = None, *, target: Any = None,
                shardings: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``target`` is an abstract or
        concrete state pytree; ``shardings`` (NamedSharding pytree) places
        restored shards directly on the mesh."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"No checkpoints under {self.directory}"
                )
        if target is not None:
            def abstractify(x, s):
                if hasattr(x, "shape"):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
                return x

            if shardings is not None:
                abstract = jax.tree.map(abstractify, target, shardings)
            else:
                abstract = jax.tree.map(lambda x: abstractify(x, None), target)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        return self._mgr.restore(step)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self):
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
