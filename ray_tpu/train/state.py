"""Train state: params + optimizer state + step, with sharding helpers.

Analog of the reference Train's per-rank model/optimizer setup
(``train/torch/train_loop_utils.py prepare_model`` + optimizer), except state
lives in ONE jit-visible pytree sharded by GSPMD — there is no per-rank
wrapper object, the mesh is the "world".
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import optax


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @staticmethod
    def create(params, optimizer: optax.GradientTransformation) -> "TrainState":
        import jax.numpy as jnp

        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), dtype=jnp.int32),
        )


def state_logical_axes(state: TrainState, param_axes) -> TrainState:
    """Logical-axis pytree for a TrainState: optimizer moments inherit the
    axes of the params they track (ZeRO-style optimizer-state sharding comes
    for free); scalars are replicated. Leaves are ``Axes`` markers so
    namedtuple-based optax states aren't mistaken for annotation leaves."""
    from ray_tpu.parallel.sharding import Axes

    params_treedef = jax.tree.structure(state.params)
    axes_tree = jax.tree.map(
        lambda a: Axes(a), param_axes, is_leaf=lambda x: isinstance(x, tuple)
    )

    def is_param_tree(x):
        """True for optimizer sub-pytrees (mu/nu moments) that mirror the
        param tree's structure — matched positionally, NOT by array shape
        (two same-shape params can have different shardings)."""
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:  # noqa: BLE001
            return False

    def annotate(node):
        if is_param_tree(node):
            return axes_tree
        shape = getattr(node, "shape", ())
        return Axes((None,) * len(shape))

    return TrainState(
        params=axes_tree,
        opt_state=jax.tree.map(annotate, state.opt_state,
                               is_leaf=is_param_tree),
        step=Axes(()),
    )
