"""ray_tpu.train: training harness + mesh trainer (reference: Ray Train,
SURVEY P14)."""

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("train")


from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.data_parallel_trainer import (
    DataParallelTrainer,
    JaxMeshTrainer,
    Result,
)
from ray_tpu.train.elastic import ElasticTrainer
from ray_tpu.train.gbdt import GBTModel, LightGBMTrainer, XGBoostTrainer
from ray_tpu.train.session import (
    get_checkpoint_dir,
    get_context,
    report,
    set_flops_per_step,
    timeit,
)
from ray_tpu.train.telemetry import StepTelemetry, record_run_bucket
from ray_tpu.train.accelerate import AccelerateTrainer
from ray_tpu.train.lightning import LightningTrainer
from ray_tpu.train.torch import TorchConfig, TorchTrainer
from ray_tpu.train.transformers import TransformersTrainer
from ray_tpu.train.trainer import JaxTrainer, TrainConfig
from ray_tpu.train.worker_group import BackendExecutor, WorkerGroup

__all__ = [
    "AccelerateTrainer",
    "LightningTrainer",
    "BackendExecutor",
    "CheckpointConfig",
    "DataParallelTrainer",
    "ElasticTrainer",
    "FailureConfig",
    "GBTModel",
    "JaxMeshTrainer",
    "JaxTrainer",
    "LightGBMTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "StepTelemetry",
    "TorchConfig",
    "TorchTrainer",
    "TransformersTrainer",
    "TrainConfig",
    "WorkerGroup",
    "XGBoostTrainer",
    "get_checkpoint_dir",
    "get_context",
    "record_run_bucket",
    "report",
    "set_flops_per_step",
    "timeit",
]
