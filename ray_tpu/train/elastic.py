"""Elastic mesh reformation: rebuild the device mesh and resume from
checkpoint when the device set changes.

SURVEY.md §7 hard-parts: "XLA collectives require all mesh processes to
enter the same program — no NCCL-style dynamic groups; elastic recovery
must rebuild whole meshes from checkpoints (make mesh-(re)formation a
first-class, fast operation)." The reference has no device-plane
elasticity at all (Train restarts whole trials from checkpoints —
``FailureConfig``); this makes the mesh rebuild itself the primitive.

The key property: the checkpoint is sharding-agnostic (Orbax OCDBT
stores the GLOBAL array), so restore places shards onto WHATEVER mesh
exists now — fewer chips after a failure, more after a scale-up — by
passing the new mesh's sharding pytree. No resharding pass, no
all-gather of the old state.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Callable

import jax

from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.trainer import JaxTrainer, TrainConfig


@dataclass
class ReformEvent:
    step: int
    old_devices: int
    new_devices: int
    seconds: float


class ElasticTrainer:
    """JaxTrainer + CheckpointManager + mesh reformation.

    ``mesh_axes_fn(n_devices) -> axes`` decides the mesh shape for any
    device count, so a reformation after losing (or gaining) chips picks
    a valid factorization automatically.
    """

    def __init__(self, model_cfg, train_cfg: TrainConfig, *,
                 checkpoint_dir: str,
                 mesh_axes_fn: Callable[[int], dict] | None = None,
                 devices=None, checkpoint_every: int = 50,
                 max_to_keep: int = 3, run_name: str | None = None):
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.mesh_axes_fn = mesh_axes_fn or (lambda n: {"dp": n})
        self.checkpoint_every = checkpoint_every
        self.run_name = run_name or \
            os.path.basename(os.path.normpath(checkpoint_dir))
        self.ckpt = CheckpointManager(checkpoint_dir,
                                      max_to_keep=max_to_keep)
        self.reform_events: list[ReformEvent] = []
        # elastic runs are driver-driven (no rank session): the trainer
        # owns its own step clock so decomposition/goodput accounting
        # matches DataParallelTrainer runs
        from ray_tpu.train.telemetry import StepTelemetry

        self.telemetry = StepTelemetry(self.run_name, 0)
        self._build(devices if devices is not None else jax.devices())

    def _build(self, devices):
        self.devices = list(devices)
        axes = self.mesh_axes_fn(len(self.devices))
        mesh = create_mesh(axes, devices=self.devices)
        self.trainer = JaxTrainer(self.model_cfg, self.train_cfg,
                                  mesh=mesh)

    # -- state lifecycle -------------------------------------------------

    def init_state(self, key):
        return self.trainer.init_state(key)

    def save(self, state, *, metrics: dict | None = None,
             force: bool = False):
        self.ckpt.save(int(state.step), state, metrics=metrics,
                       force=force)

    def restore_latest(self):
        """Restore the newest checkpoint INTO the current mesh's
        shardings (works across device-count changes)."""
        return self.ckpt.restore(
            target=self.trainer.abstract_state(),
            shardings=self.trainer.state_shardings())

    # -- reformation -----------------------------------------------------

    def reform(self, devices=None):
        """Rebuild the mesh over the (new) device set and restore the
        latest checkpoint onto it. Returns the restored state. This IS
        the elastic recovery path: call it after jax.distributed
        re-initializes with survivors."""
        t0 = time.perf_counter()
        self.ckpt.wait()  # pending async saves must be durable first
        old_n = len(self.devices)
        self._build(devices if devices is not None else jax.devices())
        state = self.restore_latest()
        event = ReformEvent(step=int(state.step), old_devices=old_n,
                            new_devices=len(self.devices),
                            seconds=time.perf_counter() - t0)
        self.reform_events.append(event)
        # reform wall clock is restart badput for the run; the step
        # clock skips past it so the gap is not double-counted into the
        # next step's residual
        from ray_tpu.train.telemetry import record_run_bucket

        record_run_bucket(self.run_name, "restart", event.seconds)
        self.telemetry.mark_gap()
        return state

    # -- driving loop ----------------------------------------------------

    def fit(self, state, data_iter, *, steps: int,
            on_metrics: Callable | None = None):
        """Train with periodic checkpoints. If a step raises (device
        failure manifests as an XLA error), the caller reforms and
        resumes; this loop only owns the happy path + checkpoint cadence.
        """
        for _ in range(steps):
            with self.telemetry.timeit("data_wait"):
                batch = next(data_iter)
            state, metrics = self.trainer.train_step(state, batch)
            step = int(metrics["step"])  # forces the async dispatch
            if on_metrics:
                on_metrics({k: float(v) for k, v in metrics.items()})
            if step % self.checkpoint_every == 0:
                with self.telemetry.timeit("checkpoint"):
                    self.save(state,
                              metrics={"loss": float(metrics["loss"])})
            self.telemetry.on_report(metrics)
        return state

    def close(self):
        self.telemetry.close()
        self.ckpt.close()
