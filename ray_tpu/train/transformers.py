"""TransformersTrainer: HuggingFace Trainer inside the rank-actor harness.

Reference analog: ``train/huggingface/transformers/transformers_trainer.py``
(the reference also ships deprecation shims for the older
``HuggingFaceTrainer`` name — ``train/huggingface/_deprecation_msg.py``).
Shape follows the reference's prepare-style API: the user builds a normal
``transformers.Trainer`` inside ``trainer_init_per_worker``; this wrapper
runs it on each rank under the torch (gloo) process group that
``TorchTrainer`` boots, wires HF's logging callbacks into
``session.report`` so Tune schedulers see intermediate metrics, and
reports the final train result with a checkpoint.

The TPU-native flagship path is ``JaxTrainer`` (XLA device plane); this
exists for capability parity with torch-ecosystem users.
"""

from __future__ import annotations

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train import session
from ray_tpu.train.torch import TorchConfig, TorchTrainer


def _wrap_hf(trainer_init_per_worker):
    def hf_loop(config):
        import transformers

        trainer = trainer_init_per_worker(config)
        if not isinstance(trainer, transformers.Trainer):
            raise TypeError(
                "trainer_init_per_worker must return a transformers.Trainer,"
                f" got {type(trainer).__name__}")

        class _ReportCallback(transformers.TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                if logs and state.is_world_process_zero:
                    metrics = {k: v for k, v in logs.items()
                               if isinstance(v, (int, float))}
                    metrics["step"] = state.global_step
                    session.report(metrics)

        trainer.add_callback(_ReportCallback())
        result = trainer.train()
        ckpt_dir = None
        ctx = session.get_context()
        if ctx.get_world_rank() == 0:
            import os

            ckpt_dir = os.path.join(ctx.get_trial_dir(), "hf_final")
            trainer.save_model(ckpt_dir)
        final = {"training_loss": float(result.training_loss),
                 "global_step": int(result.global_step)}
        session.report(final, checkpoint_dir=ckpt_dir)

    return hf_loop


class TransformersTrainer(TorchTrainer):
    """Run a ``transformers.Trainer`` on every rank worker.

    Usage::

        def trainer_init(config):
            model = AutoModelForSequenceClassification.from_pretrained(...)
            args = TrainingArguments(output_dir=..., max_steps=10, ...)
            return Trainer(model=model, args=args, train_dataset=ds)

        result = TransformersTrainer(
            trainer_init,
            scaling_config=ScalingConfig(num_workers=2),
        ).fit()

    HF's own distributed support (torch.distributed env vars) picks up the
    gloo process group the torch backend initializes, so per-rank data
    sharding and gradient averaging follow the standard HF behavior.
    """

    def __init__(self, trainer_init_per_worker, *,
                 train_loop_config: dict | None = None,
                 torch_config: TorchConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None):
        super().__init__(
            _wrap_hf(trainer_init_per_worker),
            train_loop_config=train_loop_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
