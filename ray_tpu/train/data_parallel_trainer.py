"""DataParallelTrainer: the fit() harness over rank actors.

Reference analog: ``python/ray/train/data_parallel_trainer.py``
(``DataParallelTrainer:28``, ``training_loop:418``) + ``BaseTrainer.fit``
(``base_trainer.py:571``). fit() launches the worker group, streams
rank reports, applies FailureConfig retries (restart-from-checkpoint), and
tracks top-k checkpoints per CheckpointConfig.

Result/checkpoint model: rank workers call ``ray_tpu.train.report(metrics,
checkpoint_dir=...)``; rank-0 metrics become the canonical stream. Data
ingest: pass ``datasets={"train": ds}``; each rank receives a streaming
split iterator via ``session.get_dataset_shard`` equivalent (exposed in
the config as ``config["train_shard"]``).
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import BackendExecutor


@dataclass
class Result:
    metrics: dict = field(default_factory=dict)
    checkpoint_dir: str | None = None
    error: str | None = None
    metrics_history: list = field(default_factory=list)


class _TopKCheckpoints:
    """Retention per CheckpointConfig (reference: CheckpointManager top-k,
    ``train/_internal/checkpoint_manager.py``)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.entries: list[tuple[float, str]] = []  # (score, dir)

    def add(self, checkpoint_dir: str, metrics: dict):
        if self.cfg.num_to_keep is None:
            self.entries.append((0.0, checkpoint_dir))
            return
        attr = self.cfg.checkpoint_score_attribute
        score = float(metrics.get(attr, 0.0)) if attr else float(
            len(self.entries))
        if self.cfg.checkpoint_score_order == "min":
            score = -score
        self.entries.append((score, checkpoint_dir))
        self.entries.sort(key=lambda e: e[0], reverse=True)
        while len(self.entries) > self.cfg.num_to_keep:
            _, victim = self.entries.pop()
            if victim != checkpoint_dir and os.path.isdir(victim):
                shutil.rmtree(victim, ignore_errors=True)

    def best(self) -> str | None:
        return self.entries[0][1] if self.entries else None

    def latest(self) -> str | None:
        return self.entries[-1][1] if self.entries else None


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
    ):
        self.train_fn = train_loop_per_worker
        self.config = dict(train_loop_config or {})
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        # stable run label for telemetry series/spans/annexes: the
        # RunConfig name, or a per-trainer handle when unnamed (must NOT
        # vary per attempt — restart badput accrues to the same run)
        import uuid

        self.run_name = self.run_config.name or f"run-{uuid.uuid4().hex[:8]}"

    def fit(self) -> Result:
        attempts = self.run_config.failure_config.max_failures + 1
        restore_dir = None
        last_error = None
        failed_at = None
        for attempt in range(attempts):
            result = self._run_once(restore_dir, attempt,
                                    failed_at=failed_at)
            if result.error is None:
                return result
            last_error = result.error
            restore_dir = result.checkpoint_dir  # resume from last ckpt
            failed_at = time.monotonic()
        result = Result(error=last_error, checkpoint_dir=restore_dir)
        return result

    def _run_once(self, restore_dir: str | None, attempt: int,
                  failed_at: float | None = None) -> Result:
        trial_dir = os.path.join(
            self.run_config.resolved_storage_path(),
            f"attempt_{attempt}_{int(time.time())}")
        os.makedirs(trial_dir, exist_ok=True)
        env = {}
        if restore_dir:
            env["RAY_TPU_RESTORE_CHECKPOINT"] = restore_dir
        executor = BackendExecutor(self.scaling, env=env)
        if failed_at is not None:
            # retry attempt: the teardown->respawn gap is restart badput
            from ray_tpu.train.telemetry import record_run_bucket

            record_run_bucket(self.run_name, "restart",
                              time.monotonic() - failed_at)
        manager = _TopKCheckpoints(self.run_config.checkpoint_config)
        config = dict(self.config)
        if self.datasets:
            splits = {}
            for name, ds in self.datasets.items():
                splits[name] = ds.streaming_split(self.scaling.num_workers)
            # each rank picks its shard by rank index inside the worker
            config["_dataset_splits"] = splits
        result = Result()

        def consume(reports):
            for rep in reports:
                if "error" in rep:
                    result.error = rep["error"]
                    continue
                if rep["rank"] == 0:
                    result.metrics = rep["metrics"]
                    result.metrics_history.append(rep["metrics"])
                if rep.get("checkpoint") and rep["rank"] == 0:
                    manager.add(rep["checkpoint"], rep["metrics"])

        try:
            run_refs = executor.start_training(
                _wrap_with_shard(self.train_fn), config, trial_dir,
                run_name=self.run_name)
            done = False
            while not done:
                reports, done = executor.poll_reports()
                consume(reports)
                if not done:
                    # A rank that dies BEFORE reaching the session (e.g.
                    # its train_fn fails to even deserialize) never posts
                    # mark_done — detect finished task refs so fit()
                    # surfaces the error instead of polling forever; one
                    # final drain catches late reports and the post-loop
                    # get() surfaces the task error.
                    finished, _ = ray_tpu.wait(
                        run_refs, num_returns=len(run_refs), timeout=0)
                    if len(finished) == len(run_refs):
                        consume(executor.poll_reports()[0])
                        break
                    time.sleep(0.02)
            # surface worker exceptions not routed through the bus
            try:
                ray_tpu.get(run_refs, timeout=30)
            except Exception as e:  # noqa: BLE001
                if result.error is None:
                    result.error = str(e)
        finally:
            executor.shutdown()
        result.checkpoint_dir = manager.best() or manager.latest()
        return result


def _wrap_with_shard(train_fn):
    """Give each rank its dataset shard via the session context."""

    def wrapped(config):
        # copy, never mutate: in local mode all ranks share one dict object
        splits = config.get("_dataset_splits")
        config = {k: v for k, v in config.items() if k != "_dataset_splits"}
        if splits:
            from ray_tpu.train.session import get_context

            rank = get_context().rank
            for name, split_list in splits.items():
                config[f"{name}_shard"] = split_list[rank]
        import inspect

        try:
            nparams = len(inspect.signature(train_fn).parameters)
        except (TypeError, ValueError):
            nparams = 1
        return train_fn(config) if nparams >= 1 else train_fn()

    return wrapped


class JaxMeshTrainer(DataParallelTrainer):
    """Convenience trainer: one rank per TPU host, each running the
    mesh-sharded ``JaxTrainer`` step (reference analog: TorchTrainer whose
    backend replaces init_process_group with mesh formation)."""

    def __init__(self, model_config, train_config, **kw):
        def loop(config):
            import jax

            from ray_tpu.parallel.mesh import create_mesh
            from ray_tpu.train import session
            from ray_tpu.train.trainer import JaxTrainer

            trainer = JaxTrainer(
                model_config, train_config,
                mesh=create_mesh(dict(train_config.mesh_axes)))
            state = trainer.init_state(jax.random.key(config.get("seed", 0)))
            shard = config.get("train_shard")
            steps = config.get("steps", 10)
            batch_iter = (shard.iter_jax_batches(
                batch_size=config.get("batch_size", 8))
                if shard is not None else None)
            for step in range(steps):
                with session.timeit("data_wait"):
                    if batch_iter is not None:
                        try:
                            batch = next(batch_iter)["tokens"]
                        except StopIteration:
                            break
                    else:
                        batch = jax.random.randint(
                            jax.random.key(step),
                            (config.get("batch_size", 8),
                             config.get("seq_len", 128)),
                            0, model_config.vocab_size, dtype="int32")
                if step == 0:
                    n_params = sum(
                        x.size for x in
                        jax.tree_util.tree_leaves(state.params))
                    session.set_flops_per_step(6.0 * n_params * batch.size)
                state, metrics = trainer.train_step(state, batch)
                session.report({k: float(v) for k, v in metrics.items()})

        super().__init__(loop, **kw)
