"""Central flag registry.

Analog of the reference's ``RAY_CONFIG`` macro system
(``src/ray/common/ray_config_def.h`` — 209 typed flags, each overridable via a
``RAY_<name>`` environment variable). Here: typed flags declared once, each
overridable via ``RAY_TPU_<NAME>`` env vars or a ``system_config`` dict passed
to ``init()``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    ty = type(default)
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    return raw


@dataclass
class Config:
    """Runtime configuration flags. Defaults mirror the reference's semantics
    where applicable (e.g. 5 MiB transfer chunks, ``ray_config_def.h:355``)."""

    # --- scheduling ---
    # Hybrid policy spread threshold (reference: RAY_scheduler_spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Top-k fraction of nodes considered for random tie-break in hybrid policy.
    scheduler_top_k_fraction: float = 0.2
    scheduler_top_k_absolute: int = 1
    # Max tasks a worker lease request pipelines (reference lease batching).
    max_tasks_in_flight_per_worker: int = 10

    # --- object store ---
    # Per-node shared-memory store capacity (bytes). 0 = auto (30% of RAM).
    object_store_memory: int = 0
    # Objects smaller than this stay in the owner's in-process memory store.
    max_direct_call_object_size: int = 100 * 1024
    # Node-to-node transfer chunk size (reference: 5 MiB).
    object_transfer_chunk_size: int = 5 * 1024 * 1024
    # Fraction of store capacity at which LRU eviction kicks in.
    object_store_eviction_fraction: float = 0.8
    # Enable automatic spilling to disk under memory pressure.
    object_spilling_enabled: bool = True
    # Per-node dashboard agent process (reference: dashboard/agent.py);
    # observability queries bypass the raylet data plane through it.
    dashboard_agent_enabled: bool = True
    # Spill loop thresholds: start spilling above `high`, stop below `low`
    # (fractions of store capacity; reference:
    # RAY_object_spilling_threshold + LocalObjectManager).
    object_spilling_high_fraction: float = 0.8
    object_spilling_low_fraction: float = 0.5
    # Directory for spilled object files ("" = a per-raylet temp dir).
    object_spilling_directory: str = ""
    # --- object transfer (reference: ObjectManager chunked push/pull;
    # chunk size ray_config_def.h:355, PullManager admission control
    # pull_manager.h:52) ---
    object_transfer_chunk_bytes: int = 5 << 20
    # cap on bytes in flight across all pulls, as a fraction of the
    # destination store's capacity
    object_transfer_inflight_fraction: float = 0.25

    # --- memory monitor (reference: common/memory_monitor.h:52 +
    # raylet/worker_killing_policy*.cc) ---
    # Host memory-used fraction above which the raylet kills a worker to
    # relieve pressure (reference default 0.95). <= 0 disables.
    memory_usage_threshold: float = 0.95
    # Sampling period for the monitor loop.
    memory_monitor_refresh_ms: int = 250
    # OOM kills draw from their own per-task budget (reference:
    # RAY_task_oom_retries) so host pressure — possibly caused by an
    # unrelated process — cannot burn a task's max_retries lineage budget;
    # re-dispatch backs off exponentially while pressure persists.
    task_oom_retries: int = 3

    # --- distributed reference counting (reference:
    # core_worker/reference_count.h:61 — here: per-process local counts
    # reported to a centralized GCS refcount table keyed by client id;
    # zero-count primaries are released cluster-wide) ---
    ref_counting_enabled: bool = True
    # How often each process flushes its ref-count deltas / heartbeats.
    ref_flush_interval_s: float = 0.1
    # A client (driver or worker runtime) missing heartbeats this long is
    # dead: its ref contributions are dropped and its non-detached actors
    # killed (reference: GcsActorManager owner-death handling,
    # gcs_actor_manager.cc:632). Generous by design: a falsely-reaped
    # LIVE client loses objects and actors — under a 200k-task burst the
    # control plane can delay beat processing by tens of seconds.
    client_timeout_s: float = 45.0
    # Grace before contains-edge releases propagate to inner objects
    # (covers the borrower-incref-in-flight window).
    ref_release_grace_s: float = 0.5
    # Ray-client (client://) session survival after its last connection
    # drops: a reconnecting client resumes its refs/actors within this
    # window (reference: client proxier 30s reconnect grace).
    client_reconnect_grace_s: float = 30.0
    # Client-liveness heartbeat period (empty ref_update when idle).
    # 9x margin under client_timeout_s; at 2k workers/host this is the
    # dominant idle GCS load, so it must stay coarse.
    ref_heartbeat_interval_s: float = 5.0

    # --- resource sync (reference: ray_syncer.h:86 + the raylet
    # heartbeat period, ray_config_def.h raylet_report_resources_period) ---
    # Liveness heartbeat period; the VERSIONED resource syncer (event-
    # driven, below) carries the scheduling view, so this only bounds
    # failure detection.
    raylet_heartbeat_interval_s: float = 0.5
    # Debounce for event-driven resource pushes: a dispatch burst
    # becomes one push; scheduling-view staleness ~ RPC latency + this.
    resource_sync_push_delay_s: float = 0.01
    # Ready-queue depth beyond which a submitted task spills back
    # through the GCS view even though `available` looks healthy
    # (per-task acquire/release hides saturation from averages).
    scheduler_spillback_queue_depth: int = 32
    # Hard cap on cached per-address actor-call clients (leak backstop
    # for actor churn). Must exceed the driver's LIVE actor count:
    # evicting a live client drops in-flight frames and storms resends.
    actor_client_cache_size: int = 8192
    # --- submission pipeline ---
    # Max unacked actor tasks per actor (outbox + frames in flight).
    # Deep enough that the submitter never stalls waiting for enqueue
    # acks at 10k+ calls/s (reference analog: max_pending_calls /
    # the async gRPC stream depth in DirectActorTaskSubmitter).
    actor_submit_window: int = 4096
    # Tasks packed per lease push RPC (64 measured ~20% faster than 32
    # at 4 leases; reference analog: the lease request batching).
    lease_group_size: int = 64
    # In-flight push GROUPS per lease (hides the owner round trip;
    # deeper measured WORSE — pusher-thread churn).
    lease_pipeline_depth: int = 2
    # Max concurrent leases (pusher threads) per resource shape.
    max_leases_per_shape: int = 64
    # Cached per-address actor/worker RPC clients before closed-entry
    # eviction starts (hard cap is actor_client_cache_size).
    actor_client_soft_cap: int = 256
    # Pickle-once function-export cache entries per driver.
    fn_export_cache_size: int = 512
    # Unpickle-once function cache entries per worker.
    worker_fn_cache_size: int = 256
    # Linger before flushing a burst of put-pin reports (driver) /
    # task-return reports (worker) into one batched raylet RPC.
    put_report_linger_s: float = 0.0005
    # Task events per GCS flush, and the staleness-bounding timer.
    task_event_batch_size: int = 128
    task_event_flush_interval_s: float = 2.0
    # Max ids per C call into the shm store (bounds the process-shared
    # mutex hold; ShmObjectStore.BATCH_WINDOW).
    store_batch_window: int = 4096

    # --- workers ---
    num_workers: int = 0  # 0 = num_cpus
    # How long a spawned worker may take to register before its actor
    # creation is failed (reference: worker_register_timeout_seconds).
    # A worker that DIED is detected by process polling, not this; the
    # deadline only bounds hung-but-alive spawns, so it is generous —
    # actor-flood fork storms starve fresh interpreters for >30s.
    worker_register_timeout_s: float = 600.0
    worker_lease_timeout_s: float = 30.0
    # A granted lease whose owner never dials the worker's push port is
    # handed back after this long (runtime/worker_main.py watchdog).
    lease_never_dialed_timeout_s: float = 10.0
    # Server-side parking window for a lease request before the owner is
    # told to retry (runtime/lease.py; reference: worker lease backoff).
    lease_block_s: float = 5.0

    # --- worker prestart / fork-server (runtime/prestart.py; env
    # overrides RAY_TPU_PRESTART_* — reference analog:
    # worker_pool.h:354 PrestartWorkers + idle-worker eviction knobs) ---
    # Master switch for the zygote fork path AND the demand-driven
    # prestart policy loop. Every miss (template cold, dead, containered
    # env) degrades to the plain Popen spawn.
    prestart_enabled: bool = True
    # Warm floor: forked-but-idle workers the policy loop keeps alive
    # for the default env even with an empty lease queue.
    prestart_min_workers: int = 0
    # Cumulative spawn requests for one env key before its template is
    # created. A template costs one interpreter start + the preload
    # imports; short-lived pools (a test cluster spawning a handful of
    # workers) never amortize that, so the first N-1 requests cold-spawn
    # without paying it. Burst workloads (actor fan-out) cross the
    # threshold within the first wave. An explicit warm() call,
    # prestart_min_workers > 0, or a key that once crossed the threshold
    # (respawn after template death) bypasses the gate.
    prestart_spawn_threshold: int = 8
    # Policy tick: how often lease-queue depth is sampled into a
    # prestart/evict decision.
    prestart_policy_interval_s: float = 0.25
    # Idle workers beyond the demand-predicted target older than this
    # are evicted (0 disables idle eviction; env-key mismatch eviction
    # at the cap is separate and always on).
    prestart_idle_timeout_s: float = 300.0
    # Fork request/reply deadline on the template control pipe; on
    # expiry the template is presumed wedged and killed (cold fallback).
    prestart_fork_timeout_s: float = 15.0
    # Spawn burst cap per policy tick (keeps one tick from forking the
    # whole max_workers budget at once on a deep queue).
    prestart_max_forks_per_tick: int = 8
    # Live zygote templates per node (LRU-evicted beyond this): one per
    # runtime-env key in active use.
    prestart_max_templates: int = 4

    # --- fault tolerance ---
    task_max_retries: int = 3
    # Min seconds between lineage re-submissions of the same lost object
    # (and the grace before budget exhaustion is declared terminal). Must
    # exceed the longest expected task re-execution time.
    lineage_resubmit_grace_s: float = 60.0
    # Max lineage entries the owner keeps for reconstruction (reference:
    # RAY_max_lineage_bytes); oldest dropped beyond this.
    lineage_max_entries: int = 100_000
    # LEGACY-path tasks only (placement-constrained / lease fallbacks —
    # submitted to the raylet queue, where no lease connection watches
    # them): outputs with NO location after this grace are presumed lost
    # in flight and resubmitted from lineage. Lease-path tasks never use
    # this — their owner observes the lease break synchronously.
    task_pending_resubmit_grace_s: float = 20.0
    actor_max_restarts: int = 0
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    # --- control-plane RPC retry/backoff (ReconnectingRpcClient) ---
    # Total redial window after a connection loss before the failure is
    # surfaced to the caller.
    rpc_redial_window_s: float = 10.0
    # Hard cap on redial attempts inside the window (0 = window only).
    rpc_redial_max_attempts: int = 0
    # Exponential backoff between redials: initial delay, multiplier,
    # ceiling, and jitter fraction (reference: the gRPC client retry
    # policy's exponential backoff with jitter).
    rpc_backoff_initial_s: float = 0.05
    rpc_backoff_multiplier: float = 2.0
    rpc_backoff_max_s: float = 2.0
    rpc_backoff_jitter: float = 0.2

    # --- actor control plane (batched, pipelined creation/resolution;
    # reference analog: GcsActorManager batch scheduling + the GCS
    # pubsub-driven actor table in core_worker's ActorInfoAccessor) ---
    # Driver-side registration coalescer: linger before a burst of
    # create_actor calls is flushed as ONE register_actors RPC, and the
    # max actors packed per frame.
    actor_register_linger_s: float = 0.002
    actor_register_batch_size: int = 512
    # Unacked registrations in flight before create_actor blocks
    # (memory backstop: each entry carries the pickled creation spec).
    actor_register_window: int = 8192
    # GCS placement executor: bounded worker threads fanning host_actors
    # batches out per raylet (was: one daemon thread per actor), and the
    # max placements packed per host_actors RPC.
    gcs_placement_pool_size: int = 8
    gcs_placement_batch_size: int = 256
    # Driver subscribes to CH_ACTOR and resolves locations from the
    # pushed table (get_actor polling survives only as a gap fallback).
    actor_pubsub_enabled: bool = True
    # GCS-side per-subscriber coalesce window for CH_ACTOR events: an
    # actor_ready burst becomes one framed batch per subscriber instead
    # of one inline send_msg per actor per subscriber. 0 = inline.
    actor_pubsub_flush_s: float = 0.002
    # How long the driver waits on the pushed table before falling back
    # to one counted get_actor poll (covers events published before the
    # subscription landed or lost across a redial).
    actor_resolve_fallback_s: float = 1.0
    # Hard deadline on resolving an actor's location (pushed table wait
    # + fallback polls) before the call errors ActorUnavailableError.
    # Envelope floods raise this (RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S): on
    # a saturated host the tail of a 500-actor wave can legitimately
    # take minutes to come ALIVE.
    actor_resolve_timeout_s: float = 60.0
    # Raylet-side linger coalescing worker actor_ready messages into one
    # actors_ready GCS ack batch.
    actor_ready_linger_s: float = 0.002
    # Nightly 40k control-plane axis (tests/test_actor_plane_nightly.py):
    # cumulative actors driven through the batched plane in windows.
    envelope_nightly_plane_actors: int = 40_000
    envelope_plane_window: int = 500

    # --- fault injection (runtime/fault_injection.py; env overrides
    # RAY_TPU_FAULT_INJECTION_* — the chaos tier's knobs) ---
    # Master switch: off = the plane is never consulted beyond one
    # boolean read per message.
    fault_injection_enabled: bool = False
    # Base seed for probabilistic rules (deterministic replay).
    fault_injection_seed: int = 0
    # Startup plan: inline JSON, or @/path/to/plan.json.
    fault_injection_plan: str = ""
    # Poll period for the GCS KV plan key (runtime open/heal switch).
    fault_injection_kv_poll_s: float = 0.25

    # --- TPU / device plane ---
    # Logical mesh axis names, outer to inner. ICI-contiguous inner axes.
    mesh_axis_names: str = "dp,fsdp,tp"
    # Default matmul precision for the device plane.
    default_matmul_precision: str = "bfloat16"
    # Checkpointing: async by default.
    async_checkpointing: bool = True

    # --- serve LLM engine (ray_tpu.serve.llm / paged_llm) ---
    # Steady-state decode steps per device dispatch: large chunks
    # amortize per-dispatch/tunnel overhead (throughput), small chunks
    # bound how long a new request waits behind in-flight work (TTFT).
    serve_decode_chunk: int = 16
    # Short chunk used while admissions are imminent (_use_drain_chunk).
    serve_drain_chunk: int = 8
    # KV page size (tokens) for the paged engine.
    serve_kv_page_size: int = 128
    # Prefix cache on shared prompt prefixes (chat/system prompts).
    serve_prefix_cache_enabled: bool = True
    # Continuous admission: the engine loop opens a timed admission
    # window between decode-chunk dispatches, so a request arriving
    # mid-chunk prefills behind ONE in-flight chunk instead of waiting
    # out the whole double-buffered pipeline (~2.5 chunks of
    # queue_wait measured in BENCH_r07).
    serve_continuous_admission: bool = True
    # Fraction of the EMA chunk period the admission window may wait
    # before dispatching the next chunk (the remainder covers dispatch
    # overhead so the device never idles between chunks).
    serve_admission_window_frac: float = 0.75
    # Prefix-affinity routing: handles score replicas by the longest
    # cached prefix advertised in their pushed page-hash digests and
    # fall back to power-of-two-choices when nothing matches.
    serve_prefix_routing_enabled: bool = True
    # Min interval between a replica's prefix-digest annex publishes.
    serve_digest_publish_interval_s: float = 0.2
    # A digest older than this is ignored by the router (replica dead
    # or metrics plane partitioned — fall back to p2c).
    serve_digest_ttl_s: float = 5.0
    # Proactive replica health probing: the controller pings every
    # replica on this period and replaces ones that stop answering,
    # instead of waiting for a request to trip over the corpse.
    serve_health_probing_enabled: bool = True
    serve_health_probe_period_s: float = 0.5
    serve_health_probe_timeout_s: float = 1.0
    # Consecutive probe timeouts before a replica is declared dead
    # (a typed actor-death error from the runtime is immediate).
    serve_health_probe_failures: int = 3
    # Scale-down grace: a draining replica keeps serving its in-flight
    # requests (digest retracted, route unpublished) up to this long
    # before the controller kills it anyway.
    serve_drain_timeout_s: float = 5.0

    # --- envelope / benchmark tiers (tests/test_envelope*.py) ---
    envelope_actors: int = 200
    envelope_queued_tasks: int = 20_000
    envelope_task_args: int = 1000
    envelope_nightly_actors: int = 2_000
    envelope_nightly_queued_tasks: int = 1_000_000
    envelope_nightly_task_args: int = 5_000
    # Nightly fork-pool actor axis (tests/test_envelope_nightly.py):
    # actors created through the zygote fork path in one cluster.
    envelope_nightly_fork_actors: int = 10_000
    # bench.py envelope probe sizes (bounded, driver-visible leg).
    bench_envelope_tasks: int = 100_000
    bench_envelope_actors: int = 500

    # --- observability ---
    metrics_report_interval_s: float = 2.0
    event_buffer_size: int = 10000
    log_level: str = "INFO"

    # --- cluster metrics plane (util/metrics.py + runtime/metrics_plane.py;
    # reference analog: the opencensus stats registry pushed to the node
    # metrics agent and scraped by Prometheus — here each process pushes
    # delta frames straight to the GCS time-series store) ---
    # Master switch for hot-path instrumentation AND the push loop.
    # RAY_TPU_METRICS_ENABLED=0 turns every timer into one cached
    # boolean read (the <3% overhead gate measures against this).
    metrics_enabled: bool = True
    # Delta-frame push period per process (driver / worker / raylet /
    # GCS self-ingest). Coarse by design: at 2k workers/host this is
    # idle control-plane load next to the ref heartbeat.
    metrics_push_interval_s: float = 2.0
    # Ring-buffer time-series store on the GCS: window width and how
    # many windows are kept per (metric, tags) series.
    metrics_window_s: float = 5.0
    metrics_windows: int = 60
    # Bounded pusher buffer: frames queued past this are DROPPED (the
    # plane is strictly best-effort — a slow/partitioned GCS must never
    # block or backpressure a hot path).
    metrics_push_buffer: int = 8
    # Sampling profiler riding BENCH_MODE=envelope's steady-call phase
    # (satellite of ROADMAP #2): writes a collapsed-stack artifact.
    bench_profile_enabled: bool = False

    # --- distributed tracing plane (util/tracing.py; reference analog:
    # OpenTelemetry spans exported per process — here spans ride the
    # metrics-plane push into a GCS TraceStore ring) ---
    # Per-process push ring: spans queued past this are DROPPED (same
    # drop-not-block contract as the metrics pusher buffer).
    trace_buffer_spans: int = 4096
    # Max spans shipped per pusher tick.
    trace_push_max_spans: int = 1024
    # Flight recorder: in-memory ring of recent spans + RPC events kept
    # even when collection is off, dumped on SIGTERM or on demand.
    trace_flight_spans: int = 4096
    trace_flight_window_s: float = 30.0
    # File exporter rotation cap per spans-<pid>.jsonl.
    trace_file_max_bytes: int = 64 << 20
    # Tail-based retention: normal traces are kept 1-in-N; error/slow
    # traces (any span >= trace_slow_s) always survive eviction longest.
    trace_sample_n: int = 1
    trace_slow_s: float = 1.0
    # GCS TraceStore ring bounds (traces / total spans).
    trace_store_traces: int = 512
    trace_store_spans: int = 20000
    # Default threshold for util.state.stuck_calls().
    trace_stuck_threshold_s: float = 10.0

    # --- cluster log plane (runtime/log_plane.py; reference analog:
    # per-worker session log files + log_monitor.py tailing them into
    # GCS pubsub and the dashboard) ---
    # Master switch for the in-process stdout/stderr tee in workers /
    # external raylets / external GCS (the Popen fd capture stays on
    # regardless — interpreter crashes must leave last words somewhere).
    log_capture_enabled: bool = True
    # Rotation bounds per capture file (<proc>.log, .log.1, ...):
    # rotate past log_max_bytes, keep log_rotate_count old generations
    # (env: RAY_TPU_LOG_MAX_BYTES / RAY_TPU_LOG_ROTATE_COUNT).
    log_max_bytes: int = 16 << 20
    log_rotate_count: int = 3
    # Log-monitor tail/push period and its bounded pending-entry queue:
    # entries queued past the cap are DROPPED oldest-first (same
    # drop-not-block contract as the metrics pusher buffer).
    log_push_interval_s: float = 0.25
    log_push_buffer: int = 256
    # GCS LogStore rings: recent lines kept per process, and the global
    # error ring feeding summarize_errors (deduplicated groups).
    log_store_lines: int = 2000
    log_store_error_lines: int = 2000
    log_store_error_groups: int = 256
    # Driver echo budget per SOURCE process (token bucket, lines/s): a
    # chatty worker is summarized, not allowed to bury the terminal.
    log_echo_rate_lines_s: float = 200.0
    # task_id -> (file, start, end) offset-segment annex: how many
    # recent task segments each worker publishes on its metric frames.
    log_segments_max: int = 128
    # Flight-recorder log tail (last captured lines in crash dumps).
    log_tail_lines: int = 50

    # --- cluster memory plane (runtime/refcount.py ownership snapshots,
    # object_manager occupancy decomposition, util.state.memory_summary;
    # reference analog: `ray memory` / memory_summary() aggregating every
    # core worker's reference table plus plasma occupancy) ---
    # Capture creation call sites on owned objects (one raw-frame walk
    # per put / task submission at the OWNING site only; the
    # memory_accounting_overhead_ratio fence measures with this ON).
    memory_callsite_enabled: bool = True
    # Entries per mem/owners annex payload, largest-first (the
    # remainder is counted, not shipped — the annex must stay a small
    # piggyback on metric frames, never a bulk channel).
    memory_annex_max_entries: int = 512
    # Leak detector: an owned ref older than this with zero borrowers,
    # zero submitted-task pins, zero contained-in edges, and an IDLE
    # owner is flagged (surfaced through summarize_errors()).
    memory_leak_threshold_s: float = 300.0
    # Owner idle horizon for the leak detector: a process with any ref
    # churn (non-empty flush) inside this window is considered active,
    # so a busy driver holding refs on purpose is never flagged.
    memory_leak_idle_s: float = 30.0

    # --- training telemetry plane (train/telemetry.py; reference
    # analog: Ray Train's _internal/state run tracking — here per-step
    # decomposition/MFU/goodput ride the metrics+tracing planes) ---
    # Master switch for per-step stamping. Off turns session.report's
    # telemetry hook and the goodput/annex publishes into no-ops.
    train_telemetry_enabled: bool = True
    # Progress-annex publish throttle per rank (the straggler/goodput
    # payload piggybacking on metric frames).
    train_progress_interval_s: float = 0.5
    # A rank is a straggler when it is >=1 step behind AND its last
    # step-end lags the front rank by more than this.
    train_straggler_skew_s: float = 5.0
    # On-demand cluster profiling (util/profiling.py Sampler):
    # per-request duration cap and the folded-stack table bound
    # (distinct stacks past the cap are dropped and counted).
    profile_max_duration_s: float = 30.0
    profile_folded_max_stacks: int = 10000

    def __post_init__(self):
        for f in fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def apply_overrides(self, overrides: dict | None):
        if not overrides:
            return self
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown config flag: {k!r}")
            setattr(self, k, v)
        return self


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def reset_config():
    global _global_config
    _global_config = None
