"""Unique identifiers for objects, tasks, actors, nodes, jobs, placement groups.

TPU-native analog of the reference's C++ ID layer (``src/ray/common/id.h``):
fixed-width random IDs with cheap hashing/equality, hex round-trip, and a
``nil`` sentinel. We keep them as immutable Python values (bytes-backed) so
they pickle compactly and can cross process boundaries without translation.
"""

from __future__ import annotations

import os
import threading

_HEX = "0123456789abcdef"


class BaseID:
    """Fixed-width immutable identifier backed by raw bytes."""

    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    # Entropy pool: one urandom syscall buys ~256 IDs. from_random is on
    # the per-task submit hot path (2+ IDs per call at 10k+ calls/s), and
    # a 3-4us syscall per ID is real money there. Fork safety: the pool
    # is keyed by pid so children never replay the parent's bytes.
    _pool = b""
    _pool_off = 0
    _pool_pid = 0
    _pool_lock = threading.Lock()

    @classmethod
    def from_random(cls):
        with BaseID._pool_lock:
            off = BaseID._pool_off
            pid = os.getpid()
            if off + cls.SIZE > len(BaseID._pool) or BaseID._pool_pid != pid:
                BaseID._pool = os.urandom(4096)
                BaseID._pool_pid = pid
                off = 0
            BaseID._pool_off = off + cls.SIZE
            return cls(BaseID._pool[off:off + cls.SIZE])

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    SIZE = 16


class TaskID(BaseID):
    SIZE = 12


class ActorID(BaseID):
    SIZE = 12


class NodeID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 4


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 12


class _Counter:
    """Thread-safe monotonically increasing counter (sequence numbers)."""

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value
