"""Error hierarchy.

Analog of the reference's ``python/ray/exceptions.py`` (RayError, RayTaskError,
ActorDiedError, ObjectLostError, OutOfMemoryError, GetTimeoutError, ...).
Task errors wrap the remote traceback so the driver sees the real failure site.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception. Re-raised at ``get()`` on the caller,
    carrying the remote traceback (reference: ``RayTaskError``)."""

    def __init__(self, function_name: str, cause: BaseException, tb: str | None = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"Task {function_name!r} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{self.remote_traceback}"
        )

    def __reduce__(self):
        # Exception's default reduce passes the formatted message as *args,
        # which does not match this __init__ — rebuild from fields (the
        # cause may itself be unpicklable; degrade to its repr).
        try:
            import pickle

            # round-trip: exceptions commonly fail at LOAD time (custom
            # __init__ signatures break the default args-based reduce)
            pickle.loads(pickle.dumps(self.cause))
            cause = self.cause
        except Exception:  # noqa: BLE001
            cause = RuntimeError(repr(self.cause))
        return (TaskError, (self.function_name, cause, self.remote_traceback))


class ActorError(RayTpuError):
    """Base for actor failures."""


class ActorDiedError(ActorError):
    """The actor process died (or was killed) before/while executing the call."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(f"Actor {actor_id} unavailable: {reason}")


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object value was lost from the store and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost: {reason}")


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage re-execution could not rebuild the object (retries exhausted)."""


class OwnerDiedError(ObjectLostError):
    """The object's owner process died; value unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(ref, timeout=...)`` expired before the object was ready."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ``cancel()`` before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly
    (reference: ``WorkerCrashedError``)."""


class OutOfMemoryError(RayTpuError):
    """Raised when the memory monitor kills a task/worker under host-RAM
    pressure (reference: raylet worker-killing policies)."""


class ObjectStoreFullError(RayTpuError):
    """Object store is at capacity and eviction/spilling could not make room."""


class RuntimeEnvSetupError(RayTpuError):
    """Per-task/actor runtime environment failed to materialize."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group bundles could not be reserved."""
