"""Error hierarchy.

Analog of the reference's ``python/ray/exceptions.py`` (RayError, RayTaskError,
ActorDiedError, ObjectLostError, OutOfMemoryError, GetTimeoutError, ...).
Task errors wrap the remote traceback so the driver sees the real failure site.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception. Re-raised at ``get()`` on the caller,
    carrying the remote traceback (reference: ``RayTaskError``)."""

    def __init__(self, function_name: str, cause: BaseException, tb: str | None = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"Task {function_name!r} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{self.remote_traceback}"
        )

    def __reduce__(self):
        # Exception's default reduce passes the formatted message as *args,
        # which does not match this __init__ — rebuild from fields (the
        # cause may itself be unpicklable; degrade to its repr).
        try:
            import pickle

            # round-trip: exceptions commonly fail at LOAD time (custom
            # __init__ signatures break the default args-based reduce)
            pickle.loads(pickle.dumps(self.cause))
            cause = self.cause
        except Exception:  # noqa: BLE001
            cause = RuntimeError(repr(self.cause))
        return (TaskError, (self.function_name, cause, self.remote_traceback))


class ActorError(RayTpuError):
    """Base for actor failures."""


class ActorDiedError(ActorError):
    """The actor process died (or was killed) before/while executing the call."""

    def __init__(self, actor_id=None, reason: str = "actor died",
                 restart_count: int = 0):
        self.actor_id = actor_id
        self.reason = reason
        self.restart_count = restart_count
        tail = (f" (restarted {restart_count}x)" if restart_count else "")
        super().__init__(f"Actor {actor_id} unavailable: {reason}{tail}")

    def __reduce__(self):
        return (ActorDiedError,
                (self.actor_id, self.reason, self.restart_count))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class NodeDiedError(RayTpuError):
    """The node hosting the work died (raylet process gone / heartbeat
    lost). Carries the node id and how many times the cluster supervisor
    has respawned raylets so far — a crashed peer must surface as this,
    never as a bare redial-deadline ``TimeoutError`` (reference:
    ``NodeDiedError`` / ``RayletDiedError``)."""

    def __init__(self, node_id=None, reason: str = "node died",
                 restart_count: int = 0):
        self.node_id = node_id
        self.reason = reason
        self.restart_count = restart_count
        tail = (f" (node respawned {restart_count}x)"
                if restart_count else "")
        super().__init__(f"Node {node_id} died: {reason}{tail}")

    def __reduce__(self):
        return (NodeDiedError,
                (self.node_id, self.reason, self.restart_count))


class ReplicaDiedError(ActorError):
    """A serve replica died while (or before) handling the request. The
    router raises this typed-fast for in-flight requests instead of
    letting them ride a transport redial window; carries the replica tag
    and the deployment's replacement count so callers can tell a one-off
    crash from a crash loop."""

    def __init__(self, replica_tag=None, deployment=None,
                 reason: str = "replica died", restart_count: int = 0):
        self.replica_tag = replica_tag
        self.deployment = deployment
        self.reason = reason
        self.restart_count = restart_count
        tail = (f" (deployment replaced {restart_count} replicas)"
                if restart_count else "")
        super().__init__(
            f"Replica {replica_tag} of {deployment!r} died: "
            f"{reason}{tail}")

    def __reduce__(self):
        return (ReplicaDiedError,
                (self.replica_tag, self.deployment, self.reason,
                 self.restart_count))


class ObjectLostError(RayTpuError):
    """Object value was lost from the store and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost: {reason}")


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage re-execution could not rebuild the object (retries exhausted)."""


class OwnerDiedError(ObjectLostError):
    """The object's owner process died; value unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(ref, timeout=...)`` expired before the object was ready."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ``cancel()`` before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly
    (reference: ``WorkerCrashedError``)."""


class OutOfMemoryError(RayTpuError):
    """Raised when the memory monitor kills a task/worker under host-RAM
    pressure (reference: raylet worker-killing policies)."""


class ObjectStoreFullError(RayTpuError):
    """Object store is at capacity and eviction/spilling could not make room."""


class RuntimeEnvSetupError(RayTpuError):
    """Per-task/actor runtime environment failed to materialize."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group bundles could not be reserved."""
